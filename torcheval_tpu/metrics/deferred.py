"""Deferred batch folding: make ``update()`` an O(1) host append.

TPU-first rationale. The reference's hot loop dispatches one scatter-add per
``update()`` call (``/root/reference/torcheval/metrics/functional/
classification/f1_score.py:182-190``) — cheap on CPU where dispatch is a
function call, but on an accelerator every dispatch pays an enqueue (and on
this project's tunneled chip, 0.2-5 ms of transport). Worse, per-batch
kernels are *small*: a (8192, 5) argmax+compare keeps the chip busy for tens
of microseconds; the round trip dominates by 10-100×.

So deferring metrics here do not fold per batch. ``update()`` validates shapes
(host metadata only), places the arrays, and **appends them to a pending
list**. The actual math runs later as ONE fused XLA program over the pending
batches, triggered by:

* a read of the logical state — ``compute`` / ``state_dict`` / ``to`` /
  ``merge_state`` / pickling / deepcopy / ``_prepare_for_merge_state``;
* a memory budget (``_DEFER_BUDGET_BYTES`` of pending update args) or a
  chunk-count cap (``_DEFER_MAX_CHUNKS``), so an unbounded stream folds
  periodically and pending device buffers can be freed.

Since the lane unification (ISSUE 2) the mixin carries every array-state
metric — the counter families (accuracy, F1/precision/recall, confusion),
the regression/NE sufficient-statistic metrics, and the aggregations
(Sum/Mean and, via a state-threading reduce, Max/Min) — so a whole
``MetricCollection`` folds in one XLA program per budget window and XLA
CSEs the members' shared math.

The fold itself has two physical shapes, picked at trace time per pending
signature — always ONE dispatch either way:

* **Stacked fold (the steady-loop path).** When every pending chunk shares
  one full ``(shape, dtype)`` signature — the common case in a
  constant-batch eval loop — the fold program stacks the chunks into ONE
  ``(num_chunks, batch, ...)`` operand per update argument (Podracer's
  many-logical-steps-in-one-device-program recipe, arXiv:2104.06272),
  ``jax.vmap``s the metric math (``fold_fn``) over the leading axis, and
  axis-reduces the per-chunk deltas (``sum``/``max``/``min`` matching
  ``_fold_reduce``) before ONE combine with state. The math is traced ONCE,
  so trace size and compile time are O(1) in the chunk count, and the
  retrace-signature space is O(1) per batch shape — a steady constant-batch
  loop compiles the fold at most twice per batch shape (the valve-cadence
  chunk count plus the final partial flush), which the ``obs`` recompile
  watchdog verifies. The vmap replaced the ISSUE-2 ``lax.scan``: both are
  O(1)-trace, but the scan serialized the chunks on device (K dependent
  steps of a tiny kernel — latency-bound on an accelerator) where the
  vmapped fold exposes all K×batch samples to one parallel kernel. A
  ``lax.scan`` fallback remains for third-party ``_fold_reduce`` callables
  without a known axis reduction. The stack happens INSIDE the jitted
  program: stacking on the host would pay one extra dispatch per update
  argument, and dispatches are the scarce resource on a tunneled chip.
  Applies to per-sample-reduce folds (``_fold_per_chunk``).
* **Concat fold (everything else).** Concat-regime folds
  (``_fold_per_chunk = False``) take one ``jnp.concatenate`` over the
  pending columns — their count kernels want the whole stream as a single
  large-N operand. Ragged chunk signatures under a per-sample-reduce fold
  take the per-chunk accumulation loop (correct for any shape mix, trace is
  O(chunk count) — which is why the stacked path exists). Mesh-sharded
  pending chunks also keep this path: the SPMD partitioner, not a leading
  stack axis, should own the batch dimension.

**The whole-window compiled eval step (ISSUE 6).** A ``MetricCollection``
no longer drives member ``update()`` methods per batch at all: its
``update()`` is a pure host-side accumulator appending each placed batch
ONCE to a collection-owned :class:`EvalWindow` (validation runs once per
batch signature, through the real member updates, then is memoised). When
the window closes — on the memory budget, at ``compute()`` or at
``state_dicts()`` — ONE donated pjit program (:func:`window_step`) contains
(a) every member's per-batch update math over the stacked chunks, (b) the
fold into each member's state tree, and (c), at ``compute()`` time, each
member's terminal ``_compute_fn``. ``donate_argnums`` covers both the state
trees and the chunk stack (chunks only when every chunk buffer is
library-owned — created by this collection's own host→device placement —
never buffers the caller may still hold; see ``EvalWindow.owned``).
Standalone deferred metrics ride the same program shape through
``compute()`` (:meth:`DeferredFoldMixin._deferred_compute`): fold + terminal
compute in one dispatch.

Concat-regime folds (``_fold_per_chunk = False``: confusion, F1 triples)
still see the whole stream as one large-N operand either way, so the
auto-picked lowering rides its *large-N* regime — e.g. the confusion update
at (N=1.3M, C=1000) runs the flat joint scatter at ~110M preds/s where 13
separate 100k-batch one-hot matmuls manage ~24M (docs/performance.md).

Semantics are unchanged: folding is a physical-representation change with the
same logical state (sums and extrema are order-insensitive — grouping cannot
change them beyond float associativity, and counts are integer-exact), the
same trick the reference itself plays in ``_prepare_for_merge_state``
(``metric.py:112-121``). Two visible differences, documented here:

* reading a state attribute directly (``m.num_correct``) between updates sees
  the *folded-so-far* value; go through ``state_dict()``/``compute()`` (which
  fold first) for the logical value.
* a jitted fold compiles per pending-shape signature. Steady loops (constant
  batch size) see one or two signatures; wildly varying batch shapes fall
  back to more compiles, never wrong results. Mixed signatures (e.g. a
  (N, C) score batch after (N,) label batches) flush the pending list first
  so one fold never mixes ranks.

**Slice expansion rides this machinery unchanged (ISSUE 15).** A
``SlicedMetricCollection`` member (``metrics/sliced.py``) is just a
``DeferredFoldMixin`` metric whose states carry a leading ``[num_slices]``
axis and whose chunks carry a dense int32 row column first: its fold is a
concat-regime ``_fold_fn`` ending in one segment scatter, its terminal
compute a ``jax.vmap`` over axis 0 — so the shared window, the one donated
window-step program, group folds, donation holds and the obs counters all
apply per the contracts in this module with zero sliced-specific branches
here. The layout contract (slice axis leading; a future per-window axis
outside it) lives in docs/performance.md "Sliced metrics".

Tracer transparency: when ``update`` is called inside someone else's trace
(a user jitting their eval step around a metric), deferral would leak
tracers into the pending list — so tracer args take the eager fold path,
which is exactly the pre-deferral behavior.

Donation caveat: on backends where ``donation_pipelines()`` is true, a fold
donates the previous state buffers. A raw reference captured from a state
attribute (``ref = m.num_total``) dies at the next fold — read state through
``state_dict()`` / ``compute()`` instead of holding array refs across
updates. Internally, every donated dispatch also pins its input refs until
the program retires (``_inflight_donated``): deleting a donated input's
python wrapper mid-flight blocks the host on the execution, which would
turn the async one-program window back into a synchronous one.

Observability: every fold dispatch increments ``deferred.folds{entry=,path=}``
(and ``deferred.folded_chunks{entry=}`` with the chunk count); every
whole-window step increments ``deferred.window_steps{path=}`` (and
``deferred.window_step_batches`` with the chunk count) in the obs registry
while obs is enabled — the counters a dispatch-count regression test
asserts O(1) programs per budget window on (tests/obs).
"""

from __future__ import annotations

import time
import warnings
import weakref
from functools import partial
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import _ARRAY_IMPL
from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.obs.recompile import watched_jit as _watched_jit


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


# Live unmanaged deferred metrics (round-4 verdict ask 8): when one folds, it
# scans here for peers whose pending chunks are the IDENTICAL placed arrays —
# the signature of standalone metrics fed the same batches (`cm.update(x, y);
# f1.update(x, y)` outside any collection) — and folds the whole group in one
# program, so XLA dedupes the shared math exactly as the MetricCollection
# lane does. WeakSet: registration must not keep metrics alive.
_live_deferred: "weakref.WeakSet" = weakref.WeakSet()
_defer_seq_counter = 0


def _chunks_identical(a, b) -> bool:
    """True when two pending lists hold the same chunk ARRAY OBJECTS in the
    same order — identity, not value: it is free to check and exactly
    captures "fed the same placed batches"."""
    return len(a) == len(b) and all(
        len(c) == len(h) and all(x is y for x, y in zip(c, h))
        for c, h in zip(a, b)
    )


def _is_prefix(short, long) -> bool:
    """``short`` is a (non-strict) identity-prefix of ``long``. Standalone
    metrics fed the same stream are usually one chunk apart mid-loop (A got
    batch N before B did), so exact equality would miss every
    valve-triggered fold; prefix grouping folds the common part and leaves
    the stragglers pending."""
    return len(short) <= len(long) and _chunks_identical(
        short, long[: len(short)]
    )


def _add(state, delta):
    return state + delta


def _combine(states, deltas, fold_reduce):
    """Merge ``deltas`` into ``states`` with the metric's reduce (add for
    accumulator states, max/min for extrema — the state-threading fold that
    lets non-additive states ride the same machinery). EVERY state is
    returned (merged), not just the delta'd ones: under donation all input
    buffers are invalidated, so an untouched state must still be threaded
    through to a live output buffer."""
    red = _add if fold_reduce is None else fold_reduce
    return {**states, **{n: red(states[n], d) for n, d in deltas.items()}}


def _uniform_chunks(chunks) -> bool:
    """Every chunk shares one full (shape, dtype) signature. Shapes are
    static inside a trace, so the fold bodies branch on this at TRACE time —
    the compiled program contains only the selected path."""
    head = chunks[0]
    for c in chunks[1:]:
        if len(c) != len(head):
            return False
        for x, h in zip(c, head):
            if x.shape != h.shape or x.dtype != h.dtype:
                return False
    return True


def _scan_fold(states_by_key, chunks, specs, rest=None):
    """State-threading scan fold of uniform chunks for one or more
    ``(key, fold_fn, fold_params, fold_reduce)`` specs — the single shared
    scan recipe for the solo and group dispatch bodies (each member's fold
    runs inside ONE ``lax.scan`` step, so shared math dedupes per step).

    The chunks past the first stack INSIDE the program (a host-side stack
    would pay an extra dispatch per column) into one
    ``(num_chunks - 1, batch, ...)`` operand per column, and ``lax.scan``
    folds them with the metric math traced ONCE. The first chunk folds
    OUTSIDE the scan so dtype promotion settles the carry structure (an
    int32 counter meeting a float delta promotes on the first combine; the
    scan carry must be shape/dtype-stable). A caller that already stacked
    the columns (``_stacked_fold`` with mixed vmap/scan specs) passes the
    tail as ``rest`` so one program never materializes the window twice."""

    def step(states, chunk):
        return {
            key: _combine(
                states[key], fold_fn(*chunk, *fold_params), fold_reduce
            )
            for key, fold_fn, fold_params, fold_reduce in specs
        }

    carry = step(states_by_key, chunks[0])
    if len(chunks) == 1:
        return carry
    if rest is None:
        rest = tuple(jnp.stack(cols, axis=0) for cols in zip(*chunks[1:]))
    carry, _ = jax.lax.scan(
        lambda c, chunk: (step(c, chunk), None), carry, rest
    )
    return carry


# _fold_reduce identity -> axis reduction over a stacked delta axis. The
# stacked fold reduces each state's (num_chunks, ...) delta stack with the
# matching axis kernel instead of threading a sequential carry — same
# result (sums/extrema are order-insensitive beyond float associativity),
# O(1) trace, and the chunk axis stays parallel on device. A third-party
# ``_fold_reduce`` outside this table falls back to the sequential scan.
_AXIS_REDUCERS = {None: jnp.sum, jnp.maximum: jnp.max, jnp.minimum: jnp.min}


def _stacked_fold(states_by_key, chunks, specs):
    """Parallel fold of uniform chunks for one or more ``(key, fold_fn,
    fold_params, fold_reduce, fold_vmap)`` specs — the steady-loop fold
    shape shared by the solo, group, and window-step bodies.

    The chunks stack INSIDE the program (a host-side stack would pay an
    extra dispatch per column) into one ``(num_chunks, batch, ...)`` operand
    per column; every member's fold vmaps over the leading axis in ONE
    ``jax.vmap`` (shared subcomputations dedupe per chunk, exactly as the
    old shared-scan body deduped per step), the per-chunk deltas axis-reduce
    (:data:`_AXIS_REDUCERS`), and each state combines with its delta once.
    Unlike the scan it replaced, no carry means no dtype-promotion
    staging — an int32 counter meeting a float delta promotes at the single
    combine. Specs with an exotic ``fold_reduce`` or ``fold_vmap=False``
    (fold kernels without a batching rule, e.g. ``custom_partitioning``
    lowerings) take the sequential :func:`_scan_fold` inside the same
    program."""
    scan_specs = tuple(
        s[:4] for s in specs if s[3] not in _AXIS_REDUCERS or not s[4]
    )
    specs = tuple(s for s in specs if s[3] in _AXIS_REDUCERS and s[4])
    # one stack for both lanes: when vmap and scan specs mix in one program,
    # the scan fallback slices the chunk axis of the vmap lane's stack
    # instead of stacking the same O(window-bytes) columns a second time
    # (the differing operand sets would defeat CSE)
    stacked = (
        tuple(jnp.stack(cols, axis=0) for cols in zip(*chunks))
        if specs
        else None
    )
    out = {}
    if scan_specs:
        out.update(
            _scan_fold(
                {s[0]: states_by_key[s[0]] for s in scan_specs},
                chunks,
                scan_specs,
                rest=(
                    tuple(col[1:] for col in stacked)
                    if stacked is not None and len(chunks) > 1
                    else None
                ),
            )
        )
        if not specs:
            return out

    def all_deltas(chunk):
        return {
            key: fold_fn(*chunk, *fold_params)
            for key, fold_fn, fold_params, _, _ in specs
        }

    delta_stacks = jax.vmap(all_deltas)(stacked)
    for key, _, _, fold_reduce, _ in specs:
        red = _AXIS_REDUCERS[fold_reduce]
        deltas = {n: red(v, axis=0) for n, v in delta_stacks[key].items()}
        out[key] = _combine(states_by_key[key], deltas, fold_reduce)
    return out


def _fold_deltas(chunks, fold_fn, fold_params, per_chunk, fold_reduce):
    """Deltas over the pending batches: one kernel over the concatenated
    stream (count kernels want the large-N regime), or per-chunk kernels with
    reduced deltas when the fold is per-sample independent + reduce
    (``per_chunk``) — a many-operand ``jnp.concatenate`` measured ~1.4× the
    cost of per-chunk accumulation at 200 chunks on v5e, and count kernels
    gain nothing from it there. Ragged-signature fallback for per-chunk
    folds; the steady-loop path is the scan fold (module doc)."""
    if per_chunk and len(chunks) > 1:
        red = _add if fold_reduce is None else fold_reduce
        acc = None
        for chunk in chunks:
            deltas = fold_fn(*chunk, *fold_params)
            acc = (
                deltas
                if acc is None
                else {n: red(acc[n], d) for n, d in deltas.items()}
            )
        return acc
    cat = tuple(
        jnp.concatenate(cols, axis=0) if len(cols) > 1 else cols[0]
        for cols in zip(*chunks)
    )
    return fold_fn(*cat, *fold_params)


def _fold_body(
    states,
    chunks,
    fold_fn,
    fold_params,
    per_chunk,
    fold_reduce,
    fold_vmap,
    stack_ok,
):
    if stack_ok and per_chunk and len(chunks) > 1 and _uniform_chunks(chunks):
        spec = (("s", fold_fn, fold_params, fold_reduce, fold_vmap),)
        return _stacked_fold({"s": states}, chunks, spec)["s"]
    deltas = _fold_deltas(chunks, fold_fn, fold_params, per_chunk, fold_reduce)
    return _combine(states, deltas, fold_reduce)


# Module-level jitted dispatchers shared by ALL metric instances: the trace
# cache keys on (fold_fn identity, fold_params, pending pytree signature), so
# a fresh metric instance reuses the compiled fold instead of re-tracing a
# wide concat program per instance (measured ~200 ms of host tracing for a
# 200-chunk fold — more than the fold itself; the stacked path cuts exactly
# that cost to O(1)).
# watched_jit: the deferred fold is the canonical retrace-storm site (the
# trace cache keys on the pending pytree signature — wildly varying batch
# shapes recompile the fold per signature) and the watchdog's per-signature
# counts make that visible; the scope name attributes the fold's device
# time in XLA traces.
_FOLD_STATICS = (
    "fold_fn",
    "fold_params",
    "per_chunk",
    "fold_reduce",
    "fold_vmap",
    "stack_ok",
)
_fold_dispatch = partial(
    _watched_jit, name="deferred.fold", static_argnames=_FOLD_STATICS
)(_fold_body)
_fold_dispatch_donated = partial(
    _watched_jit,
    name="deferred.fold",
    static_argnames=_FOLD_STATICS,
    donate_argnums=(0,),
)(_fold_body)


def _group_fold_core(states_by_member, chunks, specs, stack_ok):
    """Fold SEVERAL metrics' pending batches (identical args) inside one
    trace — the shared body of the group-fold and window-step programs.

    ``specs`` is a static tuple of ``(member_key, fold_fn, fold_params,
    per_chunk, fold_reduce, fold_vmap)`` — what :func:`_member_spec`
    builds. Because every member folds the same arrays
    inside one XLA program, common subcomputations dedupe: a
    MulticlassConfusionMatrix and a MulticlassF1Score over the same batch
    share the argmax and (depending on lowerings) the count kernels instead
    of dispatching them twice.

    Under a uniform pending signature (and ``stack_ok``), every per-chunk
    member folds over ONE shared stacked operand set — the members' shared
    math dedupes per chunk inside a single ``jax.vmap``
    (:func:`_stacked_fold`); concat-regime members keep their large-N
    concatenated operand in the same program.
    """
    uniform = (
        stack_ok and len(chunks) > 1 and _uniform_chunks(chunks)
    )
    out = {}
    stacked_specs = []
    for spec in specs:
        key, fold_fn, fold_params, per_chunk, fold_reduce, fold_vmap = spec
        if uniform and per_chunk:
            stacked_specs.append(
                (key, fold_fn, fold_params, fold_reduce, fold_vmap)
            )
            continue
        deltas = _fold_deltas(
            chunks, fold_fn, fold_params, per_chunk, fold_reduce
        )
        out[key] = _combine(states_by_member[key], deltas, fold_reduce)
    if stacked_specs:
        out.update(
            _stacked_fold(
                {s[0]: states_by_member[s[0]] for s in stacked_specs},
                chunks,
                tuple(stacked_specs),
            )
        )
    return out


_group_fold_dispatch = partial(
    _watched_jit,
    name="deferred.group_fold",
    static_argnames=("specs", "stack_ok"),
)(_group_fold_core)
_group_fold_dispatch_donated = partial(
    _watched_jit,
    name="deferred.group_fold",
    static_argnames=("specs", "stack_ok"),
    donate_argnums=(0,),
)(_group_fold_core)


def _window_step_body(states_by_member, chunks, specs, compute_specs, stack_ok):
    """ONE compiled eval-window step: (a) every member's per-batch update
    math over the in-program-stacked pending chunks, (b) the fold into each
    member's state tree, and (c) optionally each member's terminal compute —
    the whole window as a single XLA program ("compile the whole program,
    not the ops", arXiv:2102.04611).

    ``compute_specs`` is a static tuple of ``(member_key, compute_fn,
    compute_params, state_names)``; each listed member's ``compute_fn`` runs
    on its FOLDED states inside the same program (``state_names`` pins the
    metric's registration order — the jit pytree flattening of the states
    dict is key-sorted, so positional reads must not rely on dict order).
    Returns ``(new_states_by_member, results_by_member)``.
    """
    if chunks:
        states_by_member = _group_fold_core(
            states_by_member, chunks, specs, stack_ok
        )
    results = {}
    for key, compute_fn, compute_params, state_names in compute_specs:
        member_states = states_by_member[key]
        results[key] = compute_fn(
            *(member_states[n] for n in state_names), *compute_params
        )
    return states_by_member, results


_WINDOW_STATICS = ("specs", "compute_specs", "stack_ok")
_window_step_dispatch = partial(
    _watched_jit,
    name="deferred.window_step",
    static_argnames=_WINDOW_STATICS,
)(_window_step_body)
_window_step_dispatch_donated = partial(
    _watched_jit,
    name="deferred.window_step",
    static_argnames=_WINDOW_STATICS,
    donate_argnums=(0,),
)(_window_step_body)
# "donate everything": state trees AND the chunk stack. Only reached when
# every chunk buffer is library-owned (EvalWindow.owned — buffers this
# process created by placing a host batch, which no caller can still hold);
# XLA then may reuse the chunk HBM for outputs in place. Chunk donations
# XLA cannot alias are a no-op (the buffers free at pending-clear time
# anyway), so the runtime's "donated buffers were not usable" warning is
# suppressed at the dispatch site.
_window_step_dispatch_donated_all = partial(
    _watched_jit,
    name="deferred.window_step",
    static_argnames=_WINDOW_STATICS,
    donate_argnums=(0, 1),
)(_window_step_body)


# Donated-input lifetime: dropping the LAST python reference to a donated
# input array while its program is still executing blocks the host thread
# until the execution retires — the runtime must resolve the donation hold
# before the wrapper can die (measured 40-90 ms per eval window on XLA:CPU,
# i.e. the entire async-dispatch win of the one-program window; non-donated
# inputs delete without blocking). The buffers themselves live until the
# execution consumes them regardless, so pinning the python wrappers costs
# no device memory: every donated dispatch parks its input refs here keyed
# by one output anchor, and the next dispatch sweeps entries whose programs
# have retired (``anchor.is_ready()`` — non-blocking).
_inflight_donated: List[Tuple[Any, Tuple[Any, ...]]] = []

# newest window-step output anchor, donated or not — the overlap probe the
# double-buffered EvalWindow (and the serve ingest pool) ride: window N+1's
# fill measures itself against this anchor's is_ready(), and a pooled host
# buffer released "after the current execution" cools against it. Held
# WEAKLY: the owning metric's state binding keeps the array alive exactly
# while it is the current output; a strong global ref would pin one stale
# state buffer forever after the last window step of a quiesced process.
_last_window_anchor: Any = None


def _deref_anchor(ref: Any) -> Any:
    """A live anchor from ``ref`` — a weakref (the normal case), a direct
    anchor object (tests), or ``None``."""
    if isinstance(ref, weakref.ref):
        return ref()
    return ref


def inflight_anchor() -> Any:
    """The newest anchor that upper-bounds every in-flight execution:
    the youngest donated-hold anchor when one exists (same-device programs
    retire in submission order, so it is ready only after everything
    before it), else the last window-step output. ``None`` when nothing
    is known to be in flight."""
    if _inflight_donated:
        return _inflight_donated[-1][0]
    return _deref_anchor(_last_window_anchor)


def _hold_donated_inputs(outputs: Any, *refs: Any) -> None:
    """Pin ``refs`` (the just-donated dispatch inputs) until ``outputs``'
    program retires; sweep holds whose programs already have. A hold whose
    anchor raises on the ``is_ready`` probe was NOT necessarily retired: the
    anchor (a prior dispatch's output) gets deleted precisely when a later
    dispatch donates it, which can happen while the prior program is still
    executing (back-to-back windows: a valve fold chased by the compute()
    close). Dropping such a hold would release the prior window's donated
    inputs mid-flight — the host stall this registry exists to prevent — so
    orphaned holds re-anchor onto THIS dispatch's output instead:
    same-device programs retire in submission order, so the new anchor is
    ready only after every earlier program has retired."""
    keep = []
    orphaned = []
    retired = 0
    for anchor, held in _inflight_donated:
        try:
            if not anchor.is_ready():
                keep.append((anchor, held))
            else:
                retired += 1
        except Exception:
            orphaned.append(held)  # deleted anchor: donated to a later dispatch
    if retired and _obs._enabled:
        # flight-recorder leg of the donated-hold protocol: how many earlier
        # windows' input pins this dispatch released (their programs retired)
        _trace.instant(
            "deferred.window_step.retire", kind="window", holds=retired
        )
    anchor = next(
        (
            a
            for a in jax.tree_util.tree_leaves(outputs)
            if hasattr(a, "is_ready")
        ),
        None,
    )
    if anchor is not None:
        keep.append((anchor, (*refs, *orphaned)))
    _inflight_donated[:] = keep


def _sweep_retired_holds() -> None:
    """Drop holds whose programs have retired — called BEFORE a donated
    dispatch, while the previous dispatch's anchor is still alive (the
    dispatch itself donates-and-deletes it, after which the probe can only
    raise). Without this pre-pass the steady loop would orphan every
    window's hold into the next (the post-dispatch sweep always finds the
    anchor deleted) and the re-anchor chain would grow O(windows). A raised
    probe keeps the hold: it is re-anchored by the next
    :func:`_hold_donated_inputs`."""
    keep = []
    retired = 0
    for anchor, held in _inflight_donated:
        try:
            if anchor.is_ready():
                retired += 1
                continue
        except Exception:
            pass
        keep.append((anchor, held))
    _inflight_donated[:] = keep
    if retired and _obs._enabled:
        _trace.instant(
            "deferred.window_step.retire", kind="window", holds=retired
        )


class _quiet_unusable_donations:
    """Suppress the runtime's "donated buffers were not usable" warning
    around the library's own donated dispatches: a donation XLA cannot
    alias (a dtype/layout mismatch between a state and its successor, or a
    chunk with no matching output) is an expected no-op on these internal
    programs — the caller holds no donation decision to act on.

    A per-dispatch ``catch_warnings`` context is deliberate, despite its
    costs (it mutates process-global warning state, so a concurrent thread's
    *identical-message* warning inside the window is swallowed too, and each
    entry invalidates the interpreter's warning-registry caches): a
    module-level filter installed once would be wiped by any user or pytest
    ``catch_warnings``/``-W`` context and the warning would leak under
    strict-warnings runs. Window closes are O(windows), not O(batches), so
    the per-close cost is off the hot path."""

    def __enter__(self):
        self._ctx = warnings.catch_warnings()
        self._ctx.__enter__()
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


def _dispatch_maybe_donated(
    donate: bool, dispatch, states, chunks, held_chunks=None, **kw
):
    """Run one fold/window dispatch, applying the whole donation protocol
    when ``donate``: suppress the runtime's unusable-donation warning (these
    are library-internal programs; the caller holds no donation decision to
    act on) and pin the donated inputs until the program retires
    (:func:`_hold_donated_inputs` — dropping a donated input's python
    wrapper mid-flight blocks the host on the execution). The single owner
    of this protocol: every donated dispatch site routes through here so a
    future change to the hold/suppress rules cannot miss a path.
    ``held_chunks`` additionally pins the chunk stack when it was donated
    too (the window step's owned-chunks case)."""
    if not donate:
        return dispatch(states, chunks, **kw)
    _sweep_retired_holds()
    with _quiet_unusable_donations():
        out = dispatch(states, chunks, **kw)
    _hold_donated_inputs(out, states, held_chunks)
    return out


def _stack_allowed(chunks) -> bool:
    """Host-side gate for the stacked path: single-device pending arrays
    only. Mesh-sharded chunks keep the concat/per-chunk program — a leading
    stack axis would fight the SPMD partitioner for the batch dimension.
    (Shape uniformity is checked inside the trace, where shapes are
    static.)"""
    for a in chunks[0]:
        try:
            if len(a.sharding.device_set) != 1:
                return False
        except Exception:
            return False
    return True


def _member_spec(key, m) -> Tuple[Any, ...]:
    """Static per-member fold spec for the group/window dispatchers."""
    cls = type(m)
    return (
        key,
        cls._fold_fn,
        m._fold_params,
        cls._fold_per_chunk,
        cls._fold_reduce,
        cls._fold_vmap,
    )


def _count_fold(entry: str, path: str, n_chunks: int) -> None:
    """Obs accounting: one increment per fold *dispatch* — the quantity the
    dispatch-count regression test bounds (O(1) programs per budget window,
    never O(batches)) — plus a timeline instant so the flight recorder
    shows WHEN each legacy-lane fold fired."""
    _obs.counter("deferred.folds", entry=entry, path=path)
    _obs.counter("deferred.folded_chunks", float(n_chunks), entry=entry)
    _trace.instant(
        "deferred.fold.dispatch",
        kind="window",
        entry=entry,
        path=path,
        chunks=n_chunks,
    )


def group_fold(members: Dict[str, "DeferredFoldMixin"]) -> None:
    """Fold every member's pending batches in ONE dispatch when their pending
    structures are identical (members fed the same placed arrays through
    their own ``update``; the collection's shared-window lane uses
    :func:`window_step` instead); falls back to per-member folds
    otherwise."""
    pending = [m for m in members.values() if getattr(m, "_pending", None)]
    if not pending:
        return
    head = pending[0]._pending
    aligned = len(pending) == len(members) and all(
        _chunks_identical(m._pending, head) for m in pending[1:]
    )
    if not aligned:
        for m in pending:
            m._fold_own()
        return
    chunks = head
    # canonical POSITIONAL keys inside the program (see window_step): the
    # member names never reach the static specs or the states pytree, so
    # owners that differ only in member naming share one compiled fold
    canon = [(str(i), m) for i, m in enumerate(members.values())]
    specs = tuple(_member_spec(ck, m) for ck, m in canon)
    states = {
        ck: {n: getattr(m, n) for n in m._state_name_to_default}
        for ck, m in canon
    }
    from torcheval_tpu.utils.platform import donation_pipelines

    donate = donation_pipelines()
    dispatch = _group_fold_dispatch_donated if donate else _group_fold_dispatch
    stack_ok = _stack_allowed(chunks)
    new_states = _dispatch_maybe_donated(
        donate, dispatch, states, chunks, specs=specs, stack_ok=stack_ok
    )
    _count_fold("group_fold", "stacked" if stack_ok else "concat", len(chunks))
    # clear pending only after a successful dispatch (see _fold_own)
    for m in pending:
        m._pending = []
        m._pending_bytes = 0
    for ck, m in canon:
        for n, v in new_states[ck].items():
            setattr(m, n, v)


def window_step(
    members: Dict[str, "DeferredFoldMixin"],
    chunks: Tuple[Tuple[jax.Array, ...], ...],
    *,
    compute_keys: Iterable[str] = (),
    owned_chunks: bool = False,
) -> Dict[str, Any]:
    """Dispatch ONE whole-window program: fold ``chunks`` into every
    member's state and, for ``compute_keys`` members with a ``_compute_fn``,
    run the terminal compute on the folded states inside the same program.

    Donation ("donate everything", ISSUE 6): on ``donation_pipelines()``
    backends the state trees are always donated; the chunk stack is donated
    too when ``owned_chunks`` — the caller vouches every chunk buffer was
    created by its own placement (a caller-held buffer must never be
    donated: its next read would hit a deleted array). New states are bound
    onto the members before returning; the returned dict maps each computed
    member key to its result. Callers own pending-list clearing (only after
    this returns, so a failed dispatch never discards valid batches).

    Program sharing across owners (ISSUE 8): the member NAMES never enter
    the program — specs, compute specs and the states pytree all use
    canonical positional keys (``"0"``, ``"1"``, …, enumeration order).
    Two owners driving the same metric classes/configs over the same batch
    signature therefore hit ONE compiled window-step program whatever they
    named their members — the property that lets a multi-tenant daemon
    (``torcheval_tpu.serve``) serve hundreds of tenants from a handful of
    compiled programs instead of one per tenant."""
    compute_keys = set(compute_keys)
    canon = [(str(i), name, m) for i, (name, m) in enumerate(members.items())]
    compute_specs = tuple(
        (
            ck,
            type(m)._compute_fn,
            tuple(m._compute_params),
            tuple(m._state_name_to_default),
        )
        for ck, name, m in canon
        if name in compute_keys and type(m)._compute_fn is not None
    )
    if not chunks and not compute_specs:
        return {}
    specs = tuple(_member_spec(ck, m) for ck, _name, m in canon)
    states = {
        ck: {n: getattr(m, n) for n in m._state_name_to_default}
        for ck, _name, m in canon
    }
    from torcheval_tpu.utils.platform import donation_pipelines

    donate = donation_pipelines()
    stack_ok = _stack_allowed(chunks) if chunks else True
    donate_chunks = donate and owned_chunks and bool(chunks)
    if donate_chunks:
        dispatch = _window_step_dispatch_donated_all
    elif donate:
        dispatch = _window_step_dispatch_donated
    else:
        dispatch = _window_step_dispatch
    t0 = time.perf_counter()
    new_states, results = _dispatch_maybe_donated(
        donate,
        dispatch,
        states,
        chunks,
        held_chunks=chunks if donate_chunks else None,
        specs=specs,
        compute_specs=compute_specs,
        stack_ok=stack_ok,
    )
    global _last_window_anchor
    _anchor_leaf = next(
        (
            a
            for a in jax.tree_util.tree_leaves(new_states)
            if hasattr(a, "is_ready")
        ),
        None,
    )
    _last_window_anchor = (
        weakref.ref(_anchor_leaf) if _anchor_leaf is not None else None
    )
    path = ("stacked" if stack_ok else "concat") if chunks else "compute"
    _obs.counter("deferred.window_steps", path=path)
    if chunks:
        _obs.counter("deferred.window_step_batches", float(len(chunks)))
        # realized window occupancy as a distribution, not only a mean:
        # p50/p95 of batches-per-window is the valve-cadence health signal
        _obs.histo("deferred.window_occupancy", float(len(chunks)))
    if _obs._enabled:
        # host-side dispatch duration (the program itself runs async): the
        # timeline bar for ONE whole-window program entering the device
        _trace.complete(
            "deferred.window_step.dispatch",
            t0,
            time.perf_counter() - t0,
            kind="window",
            path=path,
            batches=len(chunks),
            computes=len(compute_specs),
            donated=bool(donate),
        )
    for ck, _name, m in canon:
        for n, v in new_states[ck].items():
            setattr(m, n, v)
    # results come back under the canonical keys; hand them to the caller
    # under the member names it asked with
    by_canon = {ck: name for ck, name, _m in canon}
    return {by_canon[ck]: v for ck, v in results.items()}


class EvalWindow:
    """Collection-owned pending-batch window shared by every deferred member.

    ``MetricCollection.update()`` appends each placed batch here ONCE
    (instead of once per member), and the window closes as a single
    :func:`window_step` program. ``owned`` tracks whether EVERY chunk buffer
    was created by the collection's own host→device placement — the
    precondition for donating the chunk stack (a buffer the caller may
    still reference is never donated). ``sig`` caches the full
    ``(shape, dtype)`` batch signature the collection's fast path was last
    validated against. ``owner`` weak-references the owning collection:
    members prune windows whose collection died (folding any orphaned
    chunks first — those updates belong to the metric whatever happened to
    the wrapper), so a long-lived metric re-wrapped per epoch never
    accumulates dead windows (:meth:`DeferredFoldMixin._live_windows`)."""

    __slots__ = (
        "members",
        "chunks",
        "nbytes",
        "sig",
        "sig_nbytes",
        "owned",
        "owner",
        "_fill_t0",
        "_ov_anchor",
        "_ov_last",
    )

    def __init__(
        self, members: Dict[str, "DeferredFoldMixin"], owner: Any = None
    ) -> None:
        self.members = members
        self.chunks: List[Tuple[jax.Array, ...]] = []
        self.nbytes = 0
        self.sig: Optional[Tuple[Any, ...]] = None
        self.sig_nbytes = 0  # cached per-batch bytes of ``sig``
        self.owned = True
        # double-buffering telemetry (ISSUE 11): this window's fill start,
        # the previous window step's output anchor if it was still
        # executing when the fill began, and the last moment that anchor
        # was observed in flight — the overlap window the
        # ``deferred.window.overlap_ms`` histogram records. Obs-gated:
        # zeroed and untouched while obs is disabled.
        self._fill_t0 = 0.0
        self._ov_anchor: Any = None
        self._ov_last = 0.0
        # ownerless windows (direct construction) count as always-alive
        self.owner = weakref.ref(owner) if owner is not None else (lambda: self)

    def append(self, chunk: Tuple[jax.Array, ...], nbytes: int, owned: bool) -> None:
        if _obs._enabled:
            # call-site guard (not inside instant()): the armed fast path
            # must not even build a labels dict while obs is disabled — the
            # host-overhead guard test pins zero obs allocations per update
            _trace.instant(
                "deferred.window.open" if not self.chunks
                else "deferred.window.append",
                kind="window",
                chunks=len(self.chunks) + 1,
                bytes=nbytes,
            )
            self._track_overlap(bool(self.chunks))
        self.chunks.append(chunk)
        self.nbytes += nbytes
        self.owned = self.owned and owned

    def _track_overlap(self, filling: bool) -> None:
        """Advance the fill-vs-previous-execution overlap watermark. On
        the first append of a window, latch the previous window step's
        anchor iff it is still executing (the double-buffer moment:
        window N+1 starts filling while window N runs); on later appends,
        move the watermark while it stays in flight. A probe that raises
        means the anchor was donated onward — its retirement time is
        unknowable, so the watermark freezes where it was (a lower
        bound, never an overclaim)."""
        now = time.perf_counter()
        if not filling:
            self._fill_t0 = now
            self._ov_anchor = None
            self._ov_last = 0.0
            anchor = _deref_anchor(_last_window_anchor)
            if anchor is not None:
                try:
                    if not anchor.is_ready():
                        self._ov_anchor = anchor
                        self._ov_last = now
                except Exception:
                    pass
        elif self._ov_anchor is not None:
            try:
                if self._ov_anchor.is_ready():
                    self._ov_anchor = None
                else:
                    self._ov_last = now
            except Exception:
                self._ov_anchor = None

    def _record_overlap(self) -> None:
        """Emit the realized fill/execute overlap for the closing window
        (obs-enabled paths only; called before this window's own
        dispatch)."""
        if not self._ov_last:
            return
        if self._ov_anchor is not None:
            try:
                if not self._ov_anchor.is_ready():
                    # still executing as the next window closes: the
                    # whole fill overlapped
                    self._ov_last = time.perf_counter()
            except Exception:
                pass
        overlap_s = self._ov_last - self._fill_t0
        if overlap_s > 0.0:
            _obs.histo("deferred.window.overlap_ms", overlap_s * 1e3)
        self._ov_anchor = None
        self._ov_last = 0.0

    def clear(self) -> None:
        self.chunks = []
        self.nbytes = 0
        self.owned = True

    def fold(self) -> None:
        """Mid-stream budget valve: fold the open window, no terminal
        compute."""
        self.close()

    def close(self, compute_keys: Iterable[str] = ()) -> Dict[str, Any]:
        """Fold everything pending into member state in O(1) programs and
        optionally run ``compute_keys`` members' terminal computes in the
        same program. Everything a computed member's logical state depends
        on folds FIRST: its OTHER collections' open windows (a metric can
        be wrapped by several collections) and stray member-own pending
        chunks (a member streamed into directly) — grouped into one program
        where their pending lists align — so a terminal compute always sees
        the member's complete stream."""
        compute_keys = tuple(compute_keys)
        if _obs._enabled:
            _trace.instant(
                "deferred.window.close",
                kind="window",
                chunks=len(self.chunks),
                computes=len(compute_keys),
            )
        for key in compute_keys:
            m = self.members.get(key)
            if m is None:
                continue
            # _live_windows (not the raw list): this is the read path a
            # collection-wrapped metric takes every epoch, so it must also
            # prune windows whose owning collection died — otherwise a
            # long-lived metric re-wrapped per epoch accumulates dead
            # windows (each pinning its collection's member dict) forever
            live = getattr(m, "_live_windows", None)
            windows = (
                live() if live is not None else getattr(m, "_defer_windows", ())
            )
            for w in windows:
                if w is not self and w.chunks:
                    w.close()
        if any(getattr(m, "_pending", None) for m in self.members.values()):
            group_fold(self.members)
        if _obs._enabled and self.chunks:
            self._record_overlap()
        chunks = tuple(self.chunks)
        results = window_step(
            self.members,
            chunks,
            compute_keys=compute_keys,
            owned_chunks=self.owned and bool(chunks),
        )
        self.clear()
        return results


class DeferredFoldMixin:
    """Mixin for array-state metrics: pending-batch cache + lazy fused fold.

    Contract for subclasses::

        def _my_fold(input, target, threshold):   # MODULE-level pure fn:
            ...                                    # math on one (stream of)
            return {"num_tp": ..., "num_fp": ...}  # batches -> {state: delta}

        def _my_compute(num_tp, num_fp, threshold):  # MODULE-level pure fn:
            return ...                               # folded states -> result

        class MyMetric(DeferredFoldMixin, Metric[jax.Array]):
            _fold_fn = staticmethod(_my_fold)
            _compute_fn = staticmethod(_my_compute)  # optional (see below)

            def __init__(self, ...):
                super().__init__(device=device)
                self._add_state(...)
                self._init_deferred()
                self._fold_params = (threshold,)   # hashable statics
                self._compute_params = (threshold,)

            def _update_check(self, input, target):
                _my_input_check(input, target)     # shape/dtype only

            def update(self, input, target):
                self._defer(self._input(input), self._input(target))
                return self

            def compute(self):
                return self._deferred_compute()

    ``_fold_fn`` must be a module-level function (shared identity across
    instances — it keys the shared jit cache) taking the update args (a whole
    concatenated stream when ``_fold_per_chunk`` is False, one chunk at a
    time otherwise) followed by ``*_fold_params``. Optional update arguments
    (a per-sample weight) defer as extra positional chunk columns; the fold
    fn discriminates on arity. Deltas merge into state with ``_fold_reduce``
    (``None`` = add; ``jnp.maximum``/``jnp.minimum`` thread extrema states).

    ``_compute_fn`` (optional) is the pure terminal compute
    ``(*states_in_registration_order, *_compute_params) -> result``; metrics
    that set it and route ``compute()`` through :meth:`_deferred_compute`
    get fold + compute fused into ONE window-step program (and their
    terminal compute rides a ``MetricCollection``'s window close). Host-side
    compute behavior (async warnings) moves to :meth:`_on_window_result`.
    ``_update_check`` (optional) holds the shape/dtype update validation —
    it runs once per batch signature and is memoised by the ``_defer`` fast
    path. ``compute``/``merge_state`` implementations that do NOT use
    ``_deferred_compute`` must call ``_fold_now()`` (and fold merge sources)
    before reading state; the :class:`Metric` base class folds in
    ``state_dict``/``to``/``_prepare_for_merge_state``/pickle.
    """

    # pending-args budget before a fold is forced. 256 MB holds e.g. 32 chunks
    # of (2^20, 5) float32 scores+labels; the fold dispatch amortises to
    # ~0.7 ns/byte of pending data even at the tunnel's worst measured
    # 5 ms/dispatch floor.
    _DEFER_BUDGET_BYTES: int = 1 << 28
    # cap on pending chunk count: bounds the stacked operand's leading axis
    # (and, on the mixed-shape fallback, the concat arity / trace size) for
    # small-batch streams. Under a steady constant-batch loop every
    # valve-triggered fold fires at exactly this count, so the stacked fold
    # sees ONE pending signature all stream long.
    _DEFER_MAX_CHUNKS: int = 256
    _defers = True  # MetricCollection: deferral is the (only) fused lane

    _fold_params: Tuple[Any, ...] = ()
    # True for folds that are per-sample independent + reduce (accuracy
    # family, regression/NE sufficient statistics, aggregations): the
    # stacked path folds chunk-wise with the math traced once, and the
    # ragged fallback accumulates per chunk — both beat a many-operand
    # concat. Count kernels (confusion, F1 triples) keep the concat to stay
    # in their measured large-N regime.
    _fold_per_chunk: bool = False
    # None = states merge by addition. Non-additive states (Max/Min extrema)
    # set a module-level combine (e.g. ``staticmethod(jnp.maximum)``) and the
    # fold threads state through it instead.
    _fold_reduce: Optional[Any] = None
    # False when the fold kernel cannot ride jax.vmap (a lowering without a
    # batching rule, e.g. custom_partitioning); such folds keep the
    # sequential lax.scan inside the stacked program.
    _fold_vmap: bool = True
    # Module-level pure terminal compute: ``_compute_fn(*states_in_
    # registration_order, *_compute_params) -> result``. Metrics that set it
    # route ``compute()`` through :meth:`_deferred_compute`, which folds any
    # pending batches AND runs this inside ONE window-step program. ``None``
    # = the metric's compute has host-side behavior (value-dependent errors,
    # blocking reads) and runs eagerly after a fold-only window close.
    _compute_fn: Optional[Any] = None
    _compute_params: Tuple[Any, ...] = ()
    # Optional signature-memoised update validation: a metric that defines
    # ``_update_check(*update_args)`` (shape/dtype checks only — it is
    # SKIPPED for a batch whose full signature matches the last validated
    # one) may drop the eager per-call check from ``update()``. ``None`` =
    # the metric validates eagerly in ``update()`` as before.
    _update_check: Optional[Any] = None

    def _init_deferred(self) -> None:
        global _defer_seq_counter
        self._pending: List[Tuple[jax.Array, ...]] = []
        self._pending_bytes = 0
        # cached (ndim, dtype, trailing-shape) signature of the chunks in
        # _pending — _defer compares one tuple instead of re-deriving the
        # head chunk's signature attribute-by-attribute on every call
        self._pending_sig: Optional[Tuple[Any, ...]] = None
        # (shapes, dtypes, nbytes) of the last VALIDATED batch: the _defer
        # fast path compares full shapes/dtypes against this and, on a hit,
        # skips validation, flush checks and the per-array nbytes reads
        # (~half the append cost on a steady loop is jax.Array.nbytes)
        self._defer_cache: Optional[Tuple[Any, ...]] = None
        # registration order: the stable tie-break for group-member ordering
        # (jit caches on the static specs tuple; WeakSet iteration order and
        # id() are both unstable)
        _defer_seq_counter += 1
        self._defer_seq = _defer_seq_counter
        _live_deferred.add(self)

    def _fold_kernel(self, *cat_args: jax.Array) -> Dict[str, jax.Array]:
        """Per-batch deltas; used directly on the tracer fallback path."""
        return type(self)._fold_fn(*cat_args, *self._fold_params)

    # -------------------------------------------------------------- machinery
    def _defer(self, *args: jax.Array) -> None:
        cache = self._defer_cache
        if cache is not None:
            shapes, dtypes, nbytes = cache
            if len(args) == len(shapes):
                # one flat loop, no genexpr/tuple allocation: a concrete
                # ArrayImpl type compare (excludes tracers for free) plus
                # per-arg shape/dtype equality against the cached signature
                for i, a in enumerate(args):
                    if (
                        type(a) is not _ARRAY_IMPL
                        or a.shape != shapes[i]
                        or a.dtype != dtypes[i]
                    ):
                        break
                else:
                    # steady-loop fast path: identical full signature to the
                    # last validated batch — the (shape-only) validation, the
                    # signature-flush check and the byte accounting are all
                    # functions of that signature, so none re-run. The budget
                    # probe inlines the (unscaled) thresholds; the full check
                    # re-tests with the managed 2x scale before acting.
                    self._pending.append(args)
                    pb = self._pending_bytes = self._pending_bytes + nbytes
                    if (
                        pb >= self._DEFER_BUDGET_BYTES
                        or len(self._pending) >= self._DEFER_MAX_CHUNKS
                    ):
                        self._defer_budget_check()
                    return
        self._defer_slow(args)

    def _defer_slow(self, args: Tuple[jax.Array, ...]) -> None:
        check = self._update_check
        if check is not None:
            # shape/dtype validation runs here (once per signature, the
            # fast path above memoises it) — tracers included: the checks
            # are host-metadata only and must surface inside a user's trace
            check(*args)
        if any(_is_tracer(a) for a in args):
            # inside an enclosing trace: fold eagerly so no tracer outlives
            # its trace in the pending list
            self._apply_deltas(self._fold_kernel(*args))
            return
        sig = tuple((a.ndim, a.dtype, a.shape[1:]) for a in args)
        if self._pending and sig != self._pending_sig:
            # arity/rank/width/dtype change: one fold never mixes signatures
            # (concatenation would be illegal or silently promote) — flush
            # the old signature FIRST, then append the new chunk
            self._fold_own()
        self._pending.append(args)
        self._pending_sig = sig
        nbytes = sum(int(a.nbytes) for a in args)
        self._pending_bytes += nbytes
        self._defer_cache = (
            tuple(a.shape for a in args),
            tuple(a.dtype for a in args),
            nbytes,
        )
        self._defer_budget_check()

    def _defer_budget_check(self) -> None:
        # _defer_managed: a MetricCollection owns the fold trigger so sibling
        # metrics fold in ONE dispatch (XLA CSEs shared math, e.g. confusion
        # matrix + F1 over the same batch). A managed member streamed into
        # DIRECTLY (bypassing the collection) still self-folds at 2x the
        # budget as a hard memory valve.
        scale = 2 if getattr(self, "_defer_managed", False) else 1
        if (
            self._pending_bytes >= scale * self._DEFER_BUDGET_BYTES
            or len(self._pending) >= scale * self._DEFER_MAX_CHUNKS
        ):
            # group first: same-stream peers are typically one chunk behind
            # right now, so the shared prefix frees (almost) everything in
            # one dispatch; fold solo only if that left us over budget
            self._group_fold_attempt()
            if (
                self._pending_bytes >= scale * self._DEFER_BUDGET_BYTES
                or len(self._pending) >= scale * self._DEFER_MAX_CHUNKS
            ):
                self._fold_own()

    def _apply_deltas(self, deltas: Dict[str, jax.Array]) -> None:
        red = type(self)._fold_reduce or _add
        for name, delta in deltas.items():
            setattr(self, name, red(getattr(self, name), delta))

    def _group_fold_attempt(self) -> None:
        """Fold the longest common pending-chunk prefix shared with live
        standalone peers in ONE program (see :data:`_live_deferred`);
        no-op without peers. Chunks past the common prefix (a peer one
        batch behind mid-stream) stay pending on their owners."""
        pending = getattr(self, "_pending", None)
        if not pending or getattr(self, "_defer_managed", False):
            return
        peers = [
            m
            for m in _live_deferred
            if m is not self
            and not getattr(m, "_defer_managed", False)
            and m.device == self.device
            and getattr(m, "_pending", None)
            and (
                _is_prefix(m._pending, pending)
                or _is_prefix(pending, m._pending)
            )
        ]
        if not peers:
            return
        # stable member order: jit caches on the static specs tuple, so the
        # same group must enumerate identically whichever member triggers
        group = sorted(
            [self, *peers],
            key=lambda m: (type(m).__qualname__, m._defer_seq),
        )
        common = min(len(m._pending) for m in group)
        chunks = self._pending[:common]
        # transitivity guard: every member must agree on the common prefix
        # (pairwise prefix vs self guarantees it, but stay explicit)
        if not all(_is_prefix(chunks, m._pending) for m in group):
            return
        specs = tuple(
            _member_spec(str(i), m) for i, m in enumerate(group)
        )
        states = {
            str(i): {n: getattr(m, n) for n in m._state_name_to_default}
            for i, m in enumerate(group)
        }
        from torcheval_tpu.utils.platform import donation_pipelines

        donate = donation_pipelines()
        dispatch = (
            _group_fold_dispatch_donated if donate else _group_fold_dispatch
        )
        stack_ok = _stack_allowed(chunks)
        new_states = _dispatch_maybe_donated(
            donate, dispatch, states, chunks, specs=specs, stack_ok=stack_ok
        )
        _count_fold(
            "group_fold", "stacked" if stack_ok else "concat", len(chunks)
        )
        for i, m in enumerate(group):
            m._pending = m._pending[common:]
            m._pending_bytes = sum(
                int(a.nbytes) for c in m._pending for a in c
            )
            for n, v in new_states[str(i)].items():
                setattr(m, n, v)

    def _live_windows(self) -> Tuple["EvalWindow", ...]:
        """The shared windows this metric still belongs to, pruning windows
        whose owning collection died — after folding any orphaned chunks
        (they carry updates the user fed; the wrapper's lifetime must not
        lose them). Keeps a long-lived metric re-wrapped per epoch from
        accumulating dead windows (and their members) forever."""
        windows = getattr(self, "_defer_windows", None)
        if not windows:
            return ()
        dead = [w for w in windows if w.owner() is None]
        for w in dead:
            if w.chunks:
                w.close()
            windows.remove(w)
        return tuple(windows)

    def _fold_now(self) -> None:
        """Fold every pending batch this metric's logical state depends on:
        EVERY collection-owned shared :class:`EvalWindow` this metric
        belongs to (their chunks carry this metric's not-yet-folded
        updates — a metric can be wrapped by several collections) and then
        the metric's own pending list."""
        for w in self._live_windows():
            if w.chunks:
                w.close()
        self._fold_own()

    def _fold_own(self) -> None:
        """Fold this metric's OWN pending batches into its state: one
        dispatch — shared with every standalone peer metric whose pending
        chunks are an identity-prefix match (see
        :meth:`_group_fold_attempt`); any remainder folds solo so the
        full-fold contract holds."""
        pending = getattr(self, "_pending", None)
        if not pending:
            return
        self._group_fold_attempt()
        pending = self._pending
        if not pending:
            return
        from torcheval_tpu.utils.platform import donation_pipelines

        # donation keeps counters updating in place in HBM; gated off on
        # tunneled backends where it serialises dispatches (utils/platform.py)
        donate = donation_pipelines()
        dispatch = _fold_dispatch_donated if donate else _fold_dispatch
        states = {n: getattr(self, n) for n in self._state_name_to_default}
        cls = type(self)
        stack_ok = _stack_allowed(pending)
        fold_kwargs = dict(
            fold_fn=cls._fold_fn,
            fold_params=self._fold_params,
            per_chunk=cls._fold_per_chunk,
            fold_reduce=cls._fold_reduce,
            fold_vmap=cls._fold_vmap,
            stack_ok=stack_ok,
        )
        new_states = _dispatch_maybe_donated(
            donate, dispatch, states, pending, **fold_kwargs
        )
        _count_fold("fold", "stacked" if stack_ok else "concat", len(pending))
        # clear pending only after a successful dispatch: a fold that raises
        # (bad batch reaching the trace) must not silently discard the valid
        # batches queued alongside it
        self._pending = []
        self._pending_bytes = 0
        for name, value in new_states.items():
            setattr(self, name, value)

    def _on_window_result(self, result):
        """Hook for host-side compute post-processing (async warnings and
        the like) applied to an in-program terminal-compute result exactly
        as the metric's own ``compute()`` would. Default: identity."""
        return result

    def _deferred_compute(self):
        """``compute()`` body for metrics with a pure ``_compute_fn``: fold
        any pending batches AND run the terminal compute inside ONE
        window-step program (a solo window step, or this member's compute
        riding the last open collection window's close — ``close()`` itself
        drains this member's earlier windows of other collections fold-only
        first). With nothing pending, the compute expression dispatches
        alone, exactly as before."""
        cls = type(self)
        open_windows = [w for w in self._live_windows() if w.chunks]
        if open_windows:
            last = open_windows[-1]
            key = next(k for k, v in last.members.items() if v is self)
            results = last.close(compute_keys=(key,))
            if key in results:
                return self._on_window_result(results[key])
        elif self._pending:
            if not getattr(self, "_defer_managed", False):
                # peers holding the same stream fold together first;
                # whatever remains is this metric's alone and fuses with
                # its compute
                self._group_fold_attempt()
            pending = tuple(self._pending)
            if pending:
                results = window_step(
                    {"s": self}, pending, compute_keys=("s",)
                )
                self._pending = []
                self._pending_bytes = 0
                if "s" in results:
                    return self._on_window_result(results["s"])
        result = cls._compute_fn(
            *(getattr(self, n) for n in self._state_name_to_default),
            *self._compute_params,
        )
        return self._on_window_result(result)

    # ------------------------------------------------------ lifecycle hooks
    def reset(self):
        for w in self._live_windows():
            if w.chunks:
                # a shared window's chunks belong to EVERY member: fold them
                # so the siblings keep their contributions (self's fold
                # lands in state this reset is about to wipe — a
                # member-level reset discards exactly its own stream,
                # nothing else's). MetricCollection.reset clears its window
                # first, so a whole-collection reset never pays this fold.
                w.close()
        self._pending = []
        self._pending_bytes = 0
        self._pending_sig = None
        self._defer_cache = None
        return super().reset()

    # NOTE no load_state_dict override: the base class folds pending chunks
    # into the OLD state before overwriting (Metric.load_state_dict), which
    # both keeps partial (strict=False) loads exact for the states they do
    # not touch and guarantees stale chunks never fold into restored state —
    # regression-tested in tests/metrics/test_deferred.py (mid-window
    # restore) and tests/resilience/test_snapshot.py.

    def __getstate__(self) -> Dict[str, Any]:
        self._fold_now()
        state = super().__getstate__()
        state["_pending"] = []
        # management (and window membership) is a live relationship with
        # collection instances; a restored/cloned metric answers to no
        # collection and must self-fold
        state.pop("_defer_managed", None)
        state.pop("_defer_windows", None)
        state.pop("_defer_cache", None)
        return state

    def __setstate__(self, state) -> None:
        super().__setstate__(state)
        # restored metrics must be visible to peers' group folds again
        self._pending = []
        self._pending_bytes = 0
        self._pending_sig = None
        self._defer_cache = None
        _live_deferred.add(self)

    def __deepcopy__(self, memo):
        self._fold_now()
        # the shared window back-references must not ride the copy: deep-
        # copying them would clone the whole collection membership (and the
        # clone answers to no collection anyway)
        d = self.__dict__
        windows = d.pop("_defer_windows", None)
        try:
            new = super().__deepcopy__(memo)
        finally:
            if windows is not None:
                d["_defer_windows"] = windows
        new.__dict__.pop("_defer_managed", None)
        new._defer_cache = None
        _live_deferred.add(new)  # clones group with future same-batch peers
        return new

from torcheval_tpu.metrics.functional.aggregation.mean import mean
from torcheval_tpu.metrics.functional.aggregation.sum import sum  # noqa: A004

__all__ = ["mean", "sum"]

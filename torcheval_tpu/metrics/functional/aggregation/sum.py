"""Weighted sum. Reference: ``torcheval/metrics/functional/aggregation/sum.py``."""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax


@jax.jit
def _sum_update(input: jax.Array, weight: jax.Array) -> jax.Array:
    return jnp.sum(input * weight)


def _weight_check(input: jax.Array, weight) -> jax.Array:
    weight = as_jax(weight, dtype=jnp.result_type(float))
    if weight.ndim != 0 and weight.shape != input.shape:
        raise ValueError(
            "weight must be a scalar or an array whose shape matches input "
            f"(input {input.shape}, weight {weight.shape})."
        )
    return weight


def sum(  # noqa: A001 - parity with reference API name
    input: jax.Array,
    weight: Union[float, int, jax.Array] = 1.0,
) -> jax.Array:
    """Compute the weighted sum of ``input``.

    Reference behavior: ``functional/aggregation/sum.py:13-56``.
    """
    input = as_jax(input)
    weight = _weight_check(input, weight)
    return _sum_update(input, weight)

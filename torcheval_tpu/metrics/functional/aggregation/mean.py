"""Weighted mean. Reference: ``torcheval/metrics/functional/aggregation/mean.py``.

Note: the reference exports ``mean`` in ``functional.__all__`` but forgets the
import (``functional/__init__.py:7,45``) — a latent export bug we fix here
(SURVEY §7 "parity with reference quirks").
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.aggregation.sum import _weight_check
from torcheval_tpu.utils.convert import as_jax


@jax.jit
def _mean_update(input: jax.Array, weight: jax.Array) -> Tuple[jax.Array, jax.Array]:
    weighted_sum = jnp.sum(input * weight)
    if weight.ndim == 0:
        total_weight = weight * input.size
    else:
        total_weight = jnp.sum(weight)
    return weighted_sum, total_weight


def mean(
    input: jax.Array,
    weight: Union[float, int, jax.Array] = 1.0,
) -> jax.Array:
    """Compute the weighted mean: ``sum(weight * input) / sum(weight)``.

    Reference behavior: ``functional/aggregation/mean.py:13-58``.
    """
    input = as_jax(input)
    weight = _weight_check(input, weight)
    weighted_sum, total_weight = _mean_update(input, weight)
    return weighted_sum / total_weight

"""Precision (binary / multiclass).

Reference: ``torcheval/metrics/functional/classification/precision.py``
(update ``:113-139``, compute ``:141-176``). Static-shape ``jnp.where``
averaging; state triple is (num_tp, num_fp, num_label) like the reference.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.ops.confusion import match_triple_counts
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.tracing import async_value_warn

_logger = logging.getLogger(__name__)

# the reference allows the string "None" here (precision.py:182)
_AVERAGE_OPTIONS = ("micro", "macro", "weighted", "None", None)


def _precision_param_check(num_classes: Optional[int], average: Optional[str]) -> None:
    if average not in _AVERAGE_OPTIONS:
        raise ValueError(
            f"`average` was not in the allowed value of {_AVERAGE_OPTIONS}, got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}."
            f" Got num_classes={num_classes}."
        )


def _precision_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _precision_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    input = input.astype(jnp.int32)
    target = target.astype(jnp.int32)
    if average == "micro":
        num_tp = (input == target).sum(dtype=jnp.int32)
        num_fp = (input != target).sum(dtype=jnp.int32)
        return num_tp, num_fp, jnp.zeros((), dtype=jnp.int32)
    # shared triple kernel (ops/confusion.py::match_triple_counts);
    # fp = predictions of a class minus its true positives
    num_tp, num_label, num_pred = match_triple_counts(
        input, target, num_classes
    )
    return num_tp, num_pred - num_tp, num_label


@partial(jax.jit, static_argnames=("average",))
def _precision_compute(
    num_tp: jax.Array,
    num_fp: jax.Array,
    num_label: jax.Array,
    average: Optional[str],
) -> jax.Array:
    num_tp = num_tp.astype(jnp.float32)
    num_fp = num_fp.astype(jnp.float32)
    num_label = num_label.astype(jnp.float32)
    denom = num_tp + num_fp
    precision = jnp.where(denom > 0, num_tp / jnp.maximum(denom, 1.0), 0.0)
    if average == "micro":
        return precision
    mask = (num_label != 0) | (denom != 0)
    if average == "macro":
        return jnp.where(mask, precision, 0.0).sum() / jnp.maximum(mask.sum(), 1)
    if average == "weighted":
        return (precision * (num_label / jnp.maximum(num_label.sum(), 1.0))).sum()
    return precision  # average in (None, "None")


@jax.jit
def _binary_precision_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    tgt = target.astype(jnp.int32)
    num_tp = (pred & tgt).sum(dtype=jnp.int32)
    num_fp = (pred & (1 - tgt)).sum(dtype=jnp.int32)
    return num_tp, num_fp, jnp.zeros((), dtype=jnp.int32)


def _warn_nan_classes(num_tp, num_fp, what: str) -> None:
    # async readback: see utils/tracing.py
    def _check(tp, fp) -> None:
        if tp.ndim and ((tp + fp) == 0).any():
            bad = np.nonzero((tp + fp) == 0)[0]
            _logger.warning(
                f"{bad.tolist()} classes have zero instances in both the predictions "
                f"and the ground truth labels. {what} is still logged as zero."
            )

    async_value_warn(_check, num_tp, num_fp)


def multiclass_precision(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """TP / (TP + FP), multiclass.

    Reference: ``functional/classification/precision.py:55-110``.
    """
    _precision_param_check(num_classes, average)
    input, target = as_jax(input), as_jax(target)
    _precision_input_check(input, target, num_classes)
    num_tp, num_fp, num_label = _precision_update(input, target, num_classes, average)
    if average in (None, "None"):
        _warn_nan_classes(num_tp, num_fp, "Precision")
    return _precision_compute(num_tp, num_fp, num_label, average)


def binary_precision(input, target, *, threshold: float = 0.5) -> jax.Array:
    """Binary precision after thresholding.

    Reference: ``functional/classification/precision.py:17-52``.
    """
    input, target = as_jax(input), as_jax(target)
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    num_tp, num_fp, num_label = _binary_precision_update(input, target, threshold)
    return _precision_compute(num_tp, num_fp, num_label, "micro")

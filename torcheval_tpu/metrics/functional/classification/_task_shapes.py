"""Shared shape validation for per-task streaming metrics.

One definition of the "``(num_samples,)`` at ``num_tasks=1``, else
``(num_tasks, num_samples)``" contract, used by normalized entropy, CTR and
calibration — the error strings stay byte-identical across the family.
"""

from __future__ import annotations

import jax


def check_task_shape(input: jax.Array, num_tasks: int) -> None:
    if num_tasks == 1:
        if input.ndim > 1:
            raise ValueError(
                "`num_tasks = 1`, `input` is expected to be one-dimensional "
                f"tensor, but got shape ({input.shape})."
            )
    elif input.ndim == 1 or input.shape[0] != num_tasks:
        raise ValueError(
            f"`num_tasks = {num_tasks}`, `input`'s shape is expected to be "
            f"({num_tasks}, num_samples), but got shape ({input.shape})."
        )


def check_num_tasks(num_tasks: int) -> None:
    if num_tasks < 1:
        raise ValueError(
            "`num_tasks` value should be greater than and equal to 1, "
            f"but received {num_tasks}."
        )

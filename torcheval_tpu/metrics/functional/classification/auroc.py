"""Binary and one-vs-all multiclass AUROC / AUPRC. Reference:
``torcheval/metrics/functional/classification/auroc.py:11-89`` (binary; the
multiclass variants are framework extensions modelled on later torcheval
releases' one-vs-all semantics).

The compute kernels live in :mod:`torcheval_tpu.ops.curves` — a static-shape
redesign of the reference's sort + dedup-mask + cumsum + trapz pipeline;
multiclass one-vs-all is the same kernel ``vmap``-ed over classes (C
independent sorts batched into one XLA program).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check as _auroc_update_input_check,
    _multiclass_precision_recall_curve_update_input_check,
)
from torcheval_tpu.ops.curves import (
    binary_auprc_kernel,
    binary_auroc_kernel,
    multiclass_auprc_kernel,
    multiclass_auroc_kernel,
)
from torcheval_tpu.utils.convert import as_jax

_MC_AVERAGE_OPTIONS = ("macro", "none", None)


def _mc_curve_param_check(num_classes: Optional[int], average) -> None:
    if average not in _MC_AVERAGE_OPTIONS:
        raise ValueError(
            f"`average` was not in the allowed value of {_MC_AVERAGE_OPTIONS}, "
            f"got {average}."
        )
    if num_classes is None or num_classes < 2:
        raise ValueError(f"num_classes must be at least 2, got {num_classes}.")


def binary_auroc(input, target) -> jax.Array:
    """Area under the ROC curve for binary classification.

    Args:
        input: predicted labels / probabilities / logits, shape ``(n_sample,)``.
        target: ground-truth binary labels, shape ``(n_sample,)``.

    Returns 0.5 when the target is all-ones or all-zeros (degenerate guard,
    reference ``auroc.py:60-66``).
    """
    input, target = as_jax(input), as_jax(target)
    _auroc_update_input_check(input, target)
    return binary_auroc_kernel(input, target)


def binary_auprc(input, target) -> jax.Array:
    """Area under the precision-recall curve (average precision) for binary
    classification.

    Framework extension (not in the reference snapshot v0.0.3; required by
    BASELINE.md config 2). Step integration matching sklearn's
    ``average_precision_score``.
    """
    input, target = as_jax(input), as_jax(target)
    _auroc_update_input_check(input, target)
    return binary_auprc_kernel(input, target)


@partial(jax.jit, static_argnames=("average",))
def _mc_average(per_class: jax.Array, average):
    return jnp.mean(per_class) if average == "macro" else per_class


def multiclass_auroc(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "macro",
) -> jax.Array:
    """One-vs-all multiclass AUROC (framework extension; later torcheval
    releases' semantics).

    Args:
        input: scores/logits ``(n_sample, num_classes)``.
        target: integer labels ``(n_sample,)``.
        average: ``"macro"`` (unweighted class mean) or ``"none"``/``None``
            (per-class vector).

    Degenerate classes (absent from ``target``, or the only class present)
    score 0.5, as in the binary degenerate guard.
    """
    _mc_curve_param_check(num_classes, average)
    input, target = as_jax(input), as_jax(target)
    _multiclass_precision_recall_curve_update_input_check(
        input, target, num_classes
    )
    return _mc_average(multiclass_auroc_kernel(input, target), average)


def multiclass_auprc(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "macro",
) -> jax.Array:
    """One-vs-all multiclass average precision (framework extension).

    Classes absent from ``target`` score 0.0 (no positives: the recall axis
    is undefined — binary AUPRC's degenerate guard, applied per class).
    """
    _mc_curve_param_check(num_classes, average)
    input, target = as_jax(input), as_jax(target)
    _multiclass_precision_recall_curve_update_input_check(
        input, target, num_classes
    )
    return _mc_average(multiclass_auprc_kernel(input, target), average)

"""Binary AUROC. Reference:
``torcheval/metrics/functional/classification/auroc.py:11-89``.

The compute kernel lives in :mod:`torcheval_tpu.ops.curves` — a static-shape
redesign of the reference's sort + dedup-mask + cumsum + trapz pipeline.
"""

from __future__ import annotations

import jax

from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check as _auroc_update_input_check,
)
from torcheval_tpu.ops.curves import binary_auprc_kernel, binary_auroc_kernel
from torcheval_tpu.utils.convert import as_jax


def binary_auroc(input, target) -> jax.Array:
    """Area under the ROC curve for binary classification.

    Args:
        input: predicted labels / probabilities / logits, shape ``(n_sample,)``.
        target: ground-truth binary labels, shape ``(n_sample,)``.

    Returns 0.5 when the target is all-ones or all-zeros (degenerate guard,
    reference ``auroc.py:60-66``).
    """
    input, target = as_jax(input), as_jax(target)
    _auroc_update_input_check(input, target)
    return binary_auroc_kernel(input, target)


def binary_auprc(input, target) -> jax.Array:
    """Area under the precision-recall curve (average precision) for binary
    classification.

    Framework extension (not in the reference snapshot v0.0.3; required by
    BASELINE.md config 2). Step integration matching sklearn's
    ``average_precision_score``.
    """
    input, target = as_jax(input), as_jax(target)
    _auroc_update_input_check(input, target)
    return binary_auprc_kernel(input, target)

"""Recall (binary / multiclass).

Reference: ``torcheval/metrics/functional/classification/recall.py``
(update ``:153-179``, compute ``:182-212``). Static-shape ``jnp.where``
averaging; NaN classes (no ground-truth instances) become zero with a warning,
as in the reference (``recall.py:195-202``).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.ops.confusion import match_triple_counts
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.tracing import async_value_warn

_logger = logging.getLogger(__name__)

_AVERAGE_OPTIONS = ("micro", "macro", "weighted", None)


def _recall_param_check(num_classes: Optional[int], average: Optional[str]) -> None:
    if average not in _AVERAGE_OPTIONS:
        raise ValueError(
            f"`average` was not in the allowed values of {_AVERAGE_OPTIONS}, "
            f"got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"`num_classes` should be a positive number when average={average}, "
            f"got num_classes={num_classes}."
        )


def _recall_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _recall_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    input = input.astype(jnp.int32)
    target = target.astype(jnp.int32)
    if average == "micro":
        num_tp = (input == target).sum(dtype=jnp.int32)
        n = jnp.asarray(target.size, dtype=jnp.int32)
        return num_tp, n, n
    # shared triple kernel (ops/confusion.py::match_triple_counts)
    num_tp, num_labels, num_predictions = match_triple_counts(
        input, target, num_classes
    )
    return num_tp, num_labels, num_predictions


@partial(jax.jit, static_argnames=("average",))
def _recall_compute(
    num_tp: jax.Array,
    num_labels: jax.Array,
    num_predictions: jax.Array,
    average: Optional[str],
) -> jax.Array:
    num_tp = num_tp.astype(jnp.float32)
    num_labels_f = num_labels.astype(jnp.float32)
    num_predictions_f = num_predictions.astype(jnp.float32)
    recall = jnp.where(
        num_labels_f > 0, num_tp / jnp.maximum(num_labels_f, 1.0), 0.0
    )
    if average == "micro":
        return recall
    if average == "macro":
        mask = (num_labels_f != 0) | (num_predictions_f != 0)
        return jnp.where(mask, recall, 0.0).sum() / jnp.maximum(mask.sum(), 1)
    if average == "weighted":
        weights = num_labels_f / jnp.maximum(num_labels_f.sum(), 1.0)
        return (recall * weights).sum()
    return recall  # average is None


@jax.jit
def _binary_recall_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    tgt = target.astype(jnp.int32)
    num_tp = (pred & tgt).sum(dtype=jnp.int32)
    num_true_labels = tgt.sum(dtype=jnp.int32)
    return num_tp, num_true_labels


def _warn_nan_recall(num_labels) -> None:
    # async readback: see utils/tracing.py
    def _check(labels) -> None:
        if labels.ndim and (labels == 0).any():
            nan_classes = np.nonzero(labels == 0)[0]
            _logger.warning(
                f"One or more NaNs identified, as no ground-truth instances of "
                f"{nan_classes.tolist()} have been seen. These have been converted to zero."
            )

    async_value_warn(_check, num_labels)


def multiclass_recall(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """TP / (TP + FN), multiclass.

    Reference: ``functional/classification/recall.py:96-151``.
    """
    _recall_param_check(num_classes, average)
    input, target = as_jax(input), as_jax(target)
    _recall_input_check(input, target, num_classes)
    num_tp, num_labels, num_predictions = _recall_update(
        input, target, num_classes, average
    )
    if average != "micro":
        _warn_nan_recall(num_labels)
    return _recall_compute(num_tp, num_labels, num_predictions, average)


def binary_recall(input, target, *, threshold: float = 0.5) -> jax.Array:
    """Binary recall after thresholding.

    Reference: ``functional/classification/recall.py:14-46``.
    """
    input, target = as_jax(input), as_jax(target)
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    num_tp, num_true_labels = _binary_recall_update(input, target, threshold)
    return _binary_recall_compute(num_tp, num_true_labels)


def _binary_recall_compute(num_tp, num_true_labels) -> jax.Array:
    # async readback: see utils/tracing.py
    def _check(n) -> None:
        if n == 0:
            _logger.warning(
                "One or more NaNs identified, as no ground-truth instances "
                "have been seen. These have been converted to zero."
            )

    async_value_warn(_check, num_true_labels)
    recall = num_tp.astype(jnp.float32) / jnp.maximum(
        num_true_labels.astype(jnp.float32), 1.0
    )
    return jnp.where(num_true_labels > 0, recall, 0.0)

"""F1 score (binary / multiclass).

Reference: ``torcheval/metrics/functional/classification/f1_score.py``
(update ``:117-191``, compute ``:194-230``). TPU notes: static-shape masked
averaging via ``jnp.where`` (no boolean indexing under jit); the reference's
weighted-average double-mask bug (``f1_score.py:228`` re-indexes the
already-masked ``num_label``) is fixed — weights are the unmasked class label
shares, matching sklearn.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.ops.confusion import match_triple_counts
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.tracing import async_value_warn

_logger = logging.getLogger(__name__)

_AVERAGE_OPTIONS = ("micro", "macro", "weighted", None)


def _f1_score_param_check(num_classes: Optional[int], average: Optional[str]) -> None:
    if average not in _AVERAGE_OPTIONS:
        raise ValueError(
            f"`average` was not in the allowed value of {_AVERAGE_OPTIONS}, got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}, "
            f"got num_classes={num_classes}."
        )


def _f1_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int], name: str
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor for {name}, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


@partial(jax.jit, static_argnames=("num_classes", "average"))
def _f1_score_update(
    input: jax.Array,
    target: jax.Array,
    num_classes: Optional[int],
    average: Optional[str],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    input = input.astype(jnp.int32)
    target = target.astype(jnp.int32)
    if average == "micro":
        num_tp = (input == target).sum(dtype=jnp.int32)
        n = jnp.asarray(target.shape[0], dtype=jnp.int32)
        return num_tp, n, n
    # shared triple kernel: one joint-key sort covers tp+label at large N
    # (ops/confusion.py::match_triple_counts)
    num_tp, num_label, num_prediction = match_triple_counts(
        input, target, num_classes
    )
    return num_tp, num_label, num_prediction


@partial(jax.jit, static_argnames=("average",))
def _f1_score_compute(
    num_tp: jax.Array,
    num_label: jax.Array,
    num_prediction: jax.Array,
    average: Optional[str],
) -> jax.Array:
    num_tp = num_tp.astype(jnp.float32)
    num_label = num_label.astype(jnp.float32)
    num_prediction = num_prediction.astype(jnp.float32)
    precision = jnp.where(
        num_prediction > 0, num_tp / jnp.maximum(num_prediction, 1.0), jnp.nan
    )
    recall = jnp.where(num_label > 0, num_tp / jnp.maximum(num_label, 1.0), jnp.nan)
    f1 = 2 * precision * recall / (precision + recall)
    f1 = jnp.nan_to_num(f1)
    if average == "micro":
        return f1
    # classes absent from both target and predictions are excluded from the
    # macro mean (reference mask at f1_score.py:210-216)
    mask = (num_label != 0) | (num_prediction != 0)
    if average == "macro":
        return jnp.where(mask, f1, 0.0).sum() / jnp.maximum(mask.sum(), 1)
    if average == "weighted":
        # fixed vs reference bug (:228): weights are unmasked label shares
        weights = num_label / jnp.maximum(num_label.sum(), 1.0)
        return (f1 * weights).sum()
    return f1  # average in (None,)


@jax.jit
def _binary_f1_score_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    num_tp = (pred * target).sum(dtype=jnp.int32)
    num_label = target.sum(dtype=jnp.int32)
    num_prediction = pred.sum(dtype=jnp.int32)
    return num_tp, num_label, num_prediction


def _warn_empty_classes(num_label) -> None:
    # async: the readback otherwise blocks compute() on the device stream
    # (a full tunnel RTT on this project's chip) — utils/tracing.py
    def _check(labels) -> None:
        if labels.ndim and (labels == 0).any():
            _logger.warning(
                "Some classes do not exist in the target. "
                "F1 scores for these classes will be cast to zeros."
            )

    async_value_warn(_check, num_label)


def multiclass_f1_score(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    average: Optional[str] = "micro",
) -> jax.Array:
    """Harmonic mean of precision and recall, multiclass.

    Reference: ``functional/classification/f1_score.py:52-114``.
    """
    _f1_score_param_check(num_classes, average)
    input, target = as_jax(input), as_jax(target)
    _f1_input_check(input, target, num_classes, "multiclass f1 score")
    num_tp, num_label, num_prediction = _f1_score_update(
        input, target, num_classes, average
    )
    if average != "micro":
        _warn_empty_classes(num_label)
    return _f1_score_compute(num_tp, num_label, num_prediction, average)


def binary_f1_score(input, target, *, threshold: float = 0.5) -> jax.Array:
    """Binary F1 after thresholding ``input``.

    Reference: ``functional/classification/f1_score.py:16-49``.
    """
    input, target = as_jax(input), as_jax(target)
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor for binary f1 score, got shape {input.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor for binary f1 score, got shape {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )
    num_tp, num_label, num_prediction = _binary_f1_score_update(
        input, target, threshold
    )
    return _f1_score_compute(num_tp, num_label, num_prediction, "micro")

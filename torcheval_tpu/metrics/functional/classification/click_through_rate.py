"""Click-through rate: weighted click frequency.

Extension beyond the reference snapshot (which ships no CTR metric; its CTR
*calibration* companion is ``binary_normalized_entropy``, reference
``torcheval/metrics/functional/classification/binary_normalized_entropy.py``).
Modeled on the upstream torcheval windowed/CTR family's semantics:
``ctr = sum(weight * clicks) / sum(weight)`` per task, ``0.0`` when no
weight has been seen. Sufficient statistics — ``click_total`` and
``weight_total`` — are both SUM-mergeable, so the class metric syncs on the
typed wire like every counter.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_task_shape,
)
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.numerics import safe_div


def _ctr_input_check(
    input: jax.Array, num_tasks: int, weights: Optional[jax.Array]
) -> None:
    if weights is not None and getattr(weights, "ndim", 0) and (
        input.shape != weights.shape
    ):
        raise ValueError(
            f"`weights` shape ({weights.shape}) is different from `input` "
            f"shape ({input.shape})"
        )
    check_task_shape(input, num_tasks)


@jax.jit
def _ctr_fold(
    input: jax.Array, weights: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    input = input.astype(jnp.float32)
    w = jnp.broadcast_to(jnp.asarray(weights, jnp.float32), input.shape)
    return jnp.sum(w * input, axis=-1), jnp.sum(w, axis=-1)


def _click_through_rate_update(
    input: jax.Array,
    num_tasks: int,
    weights: Union[float, int, jax.Array, None],
) -> Tuple[jax.Array, jax.Array]:
    if weights is None:
        weights = 1.0
    elif not isinstance(weights, (int, float)):
        # convert BEFORE the check: a python list has no .shape and would
        # bypass the documented shape validation
        weights = as_jax(weights)
    _ctr_input_check(input, num_tasks, weights if hasattr(weights, "shape") else None)
    return _ctr_fold(input, as_jax(weights))


@jax.jit
def _ctr_compute(click_total: jax.Array, weight_total: jax.Array) -> jax.Array:
    # 0.0 when nothing was weighed in (shared zero-denominator convention)
    return safe_div(click_total, weight_total)


def click_through_rate(
    input,
    weights: Union[float, int, jax.Array, None] = None,
    *,
    num_tasks: int = 1,
) -> jax.Array:
    """``sum(weights * input) / sum(weights)`` — the weighted click rate.

    Args:
        input: click indicators (0/1), shape ``(num_samples,)`` or
            ``(num_tasks, num_samples)``.
        weights: optional per-sample weights (scalar or same shape as
            ``input``); default 1.
        num_tasks: number of parallel tasks (leading axis when > 1).

    Returns ``0.0`` (per task) when the total weight is zero.
    """
    input = as_jax(input)
    clicks, total = _click_through_rate_update(input, num_tasks, weights)
    return _ctr_compute(clicks, total)

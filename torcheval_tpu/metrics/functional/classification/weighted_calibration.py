"""Weighted calibration: predicted positives over observed positives.

Extension beyond the reference snapshot (no calibration-ratio metric ships
there; the nearest neighbour is ``binary_normalized_entropy``, reference
``torcheval/metrics/functional/classification/binary_normalized_entropy.py``).
``calibration = sum(weight * input) / sum(weight * target)`` per task — the
standard ads-ranking check that predicted click probability mass matches
observed clicks (1.0 = perfectly calibrated, > 1 over-predicts). ``0.0``
when no positive labels have been seen. Both sufficient statistics are
SUM-mergeable scalars per task.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_task_shape,
)
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.numerics import safe_div


def _calibration_input_check(
    input: jax.Array,
    target: jax.Array,
    num_tasks: int,
    weight: Optional[jax.Array],
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            f"`input` shape ({input.shape}) is different from `target` shape "
            f"({target.shape})"
        )
    if weight is not None and getattr(weight, "ndim", 0) and (
        input.shape != weight.shape
    ):
        raise ValueError(
            f"`weight` shape ({weight.shape}) is different from `input` "
            f"shape ({input.shape})"
        )
    check_task_shape(input, num_tasks)


@jax.jit
def _calibration_fold(
    input: jax.Array, target: jax.Array, weight: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    input = input.astype(jnp.float32)
    target = target.astype(jnp.float32)
    w = jnp.broadcast_to(jnp.asarray(weight, jnp.float32), input.shape)
    return jnp.sum(w * input, axis=-1), jnp.sum(w * target, axis=-1)


def _weighted_calibration_update(
    input: jax.Array,
    target: jax.Array,
    num_tasks: int,
    weight: Union[float, int, jax.Array, None],
) -> Tuple[jax.Array, jax.Array]:
    if weight is None:
        weight = 1.0
    elif not isinstance(weight, (int, float)):
        # convert BEFORE the check: a python list has no .shape and would
        # bypass the documented shape validation
        weight = as_jax(weight)
    _calibration_input_check(
        input, target, num_tasks, weight if hasattr(weight, "shape") else None
    )
    return _calibration_fold(input, target, as_jax(weight))


@jax.jit
def _calibration_compute(
    weighted_input_sum: jax.Array, weighted_label_sum: jax.Array
) -> jax.Array:
    # 0.0 when no positive label mass (shared zero-denominator convention)
    return safe_div(weighted_input_sum, weighted_label_sum)


def weighted_calibration(
    input,
    target,
    weight: Union[float, int, jax.Array, None] = None,
    *,
    num_tasks: int = 1,
) -> jax.Array:
    """``sum(weight * input) / sum(weight * target)`` per task.

    Args:
        input: predicted probabilities, shape ``(num_samples,)`` or
            ``(num_tasks, num_samples)``.
        target: binary labels, same shape.
        weight: optional per-sample weights (scalar or same shape); default 1.
        num_tasks: number of parallel tasks (leading axis when > 1).

    Returns ``0.0`` (per task) when no positive label mass has been seen.
    """
    input, target = as_jax(input), as_jax(target)
    pred, label = _weighted_calibration_update(input, target, num_tasks, weight)
    return _calibration_compute(pred, label)

"""Confusion matrix (multiclass / binary).

Not present in the reference snapshot (v0.0.3) but required by the benchmark
target (BASELINE.md config 3: "MulticlassConfusionMatrix + F1, num_classes=
1000, ImageNet eval"); API modelled on later torcheval / sklearn conventions.
Rows are true classes, columns predicted classes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.ops.confusion import confusion_matrix_counts
from torcheval_tpu.utils.convert import as_jax

_NORMALIZE_OPTIONS = (None, "all", "pred", "true")


def _confusion_matrix_param_check(num_classes, normalize) -> None:
    if num_classes is None or num_classes < 2:
        raise ValueError(f"num_classes must be at least 2, got {num_classes}.")
    if normalize not in _NORMALIZE_OPTIONS:
        raise ValueError(
            f"normalize must be one of {_NORMALIZE_OPTIONS}, got {normalize}."
        )


def _confusion_matrix_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int] = None
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


def multiclass_confusion_matrix(
    input,
    target,
    num_classes: int,
    *,
    normalize: Optional[str] = None,
) -> jax.Array:
    """(num_classes, num_classes) confusion counts; ``input`` may be labels
    ``(n,)`` or scores ``(n, c)`` (argmax applied)."""
    _confusion_matrix_param_check(num_classes, normalize)
    input, target = as_jax(input), as_jax(target)
    _confusion_matrix_input_check(input, target, num_classes)
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    return confusion_matrix_counts(input, target, num_classes, normalize=normalize)


def binary_confusion_matrix(
    input,
    target,
    *,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
) -> jax.Array:
    """2x2 confusion counts after thresholding scores."""
    if normalize not in _NORMALIZE_OPTIONS:
        raise ValueError(
            f"normalize must be one of {_NORMALIZE_OPTIONS}, got {normalize}."
        )
    input, target = as_jax(input), as_jax(target)
    _confusion_matrix_input_check(input, target)
    pred = jnp.where(input < threshold, 0, 1)
    return confusion_matrix_counts(pred, target, 2, normalize=normalize)

"""Precision-recall curves (binary / multiclass). Reference:
``torcheval/metrics/functional/classification/precision_recall_curve.py``.

Curve lengths are data-dependent (one point per distinct threshold), which
JAX cannot express inside jit. Strategy per SURVEY §7: the device kernel
(:func:`torcheval_tpu.ops.curves.prc_points_kernel`) produces full-length
curves plus a validity mask in one compiled sort pass; the API boundary trims
and flips on the host. The hot path for streaming/binned evaluation is the
static-shaped :mod:`binned_precision_recall_curve` family.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.ops.curves import class_onehot_rows, multiclass_prc_points_kernel, prc_points_kernel
from torcheval_tpu.utils.convert import as_jax


def _binary_precision_recall_curve_update_input_check(
    input: jax.Array, target: jax.Array
) -> None:
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same shape, "
            f"got shapes {input.shape} and {target.shape}."
        )


def _multiclass_precision_recall_curve_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int]
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample, num_classes), "
            f"got {input.shape} and num_classes={num_classes}."
        )


def _trim_curve(
    thresholds: np.ndarray,
    precision: np.ndarray,
    recall: np.ndarray,
    last: np.ndarray,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Host-side: select tie-group ends, flip to ascending-threshold order,
    append the (precision=1, recall=0) graph-origin point (reference
    ``precision_recall_curve.py:224-230``)."""
    p = precision[last][::-1]
    r = recall[last][::-1]
    t = thresholds[last][::-1]
    p = np.concatenate([p, np.ones(1, dtype=p.dtype)])
    r = np.concatenate([r, np.zeros(1, dtype=r.dtype)])
    return jnp.asarray(p), jnp.asarray(r), jnp.asarray(t)


def binary_precision_recall_curve(
    input, target
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Precision-recall pairs and thresholds for binary classification.

    Args:
        input: probabilities / logits, shape ``(n_sample,)``.
        target: binary labels, shape ``(n_sample,)``.

    Returns:
        ``(precision, recall, thresholds)`` with shapes
        ``(k+1,), (k+1,), (k,)`` for ``k`` distinct thresholds; recall is 1.0
        everywhere when the target has no positives.
    """
    input, target = as_jax(input), as_jax(target)
    _binary_precision_recall_curve_update_input_check(input, target)
    s, p, r, last = prc_points_kernel(input, target)
    return _trim_curve(
        np.asarray(s), np.asarray(p), np.asarray(r), np.asarray(last)
    )


def multiclass_precision_recall_curve(
    input, target, *, num_classes: Optional[int] = None
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """One-vs-all precision-recall curves for each class.

    Args:
        input: scores/logits ``(n_sample, num_classes)``.
        target: class indices ``(n_sample,)``.
        num_classes: defaults to ``input.shape[1]``.

    Returns:
        ``(precision, recall, thresholds)`` — each a list with one
        variable-length array per class (reference layout).
    """
    input, target = as_jax(input), as_jax(target)
    if num_classes is None and input.ndim == 2:
        num_classes = input.shape[1]
    _multiclass_precision_recall_curve_update_input_check(
        input, target, num_classes
    )
    onehot = class_onehot_rows(target, num_classes).astype(
        jnp.float32
    )
    s, p, r, last = multiclass_prc_points_kernel(input.T, onehot)
    s, p, r, last = map(np.asarray, (s, p, r, last))
    precisions, recalls, thresholds = [], [], []
    for c in range(num_classes):
        pc, rc, tc = _trim_curve(s[c], p[c], r[c], last[c])
        precisions.append(pc)
        recalls.append(rc)
        thresholds.append(tc)
    return precisions, recalls, thresholds

"""Binary normalized (cross-)entropy. Reference:
``torcheval/metrics/functional/classification/binary_normalized_entropy.py``.

NE = (observed cross entropy) / (entropy of the base positive rate) — the
standard CTR-prediction calibration metric. Sufficient statistics per task:
``total_entropy``, ``num_examples``, ``num_positive`` — all SUM-mergeable.

The reference accumulates in float64 (``binary_normalized_entropy.py:76-87``).
TPU has no fast fp64, so we accumulate in float32 and note that per-batch
summation keeps error at O(sqrt(num_batches)) ulp; exactness-critical users
can pre-sum on host.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_task_shape,
)
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.tracing import host_resident

_EPS = 1.1920929e-07  # float32 eps, mirroring the reference's float64 clamp


def _ne_value_check(source, from_logits: bool) -> None:
    """[0, 1] probability check against a HOST-resident value source (the
    raw pre-placement numpy/torch input, or a CPU-committed jax array).
    Device-resident sources skip: reading them back would block the async
    dispatch stream on every update (documented divergence from the
    reference's always-eager check, binary_normalized_entropy.py:145-152) —
    the log-clamp in the fold keeps the math finite either way."""
    if from_logits or source is None or not host_resident(source):
        return
    import numpy as np

    arr = np.asarray(source)
    if arr.size and (arr.max() > 1.0 or arr.min() < 0.0):
        raise ValueError(
            f"`from_logits`={from_logits}, `input` should be probability "
            f"in range [0., 1.], but got `input` ranging from {arr.min()} "
            f"to {arr.max()}. Please set `from_logits = True` or convert "
            "`input` into valid probability value."
        )


def _ne_input_check(
    input: jax.Array,
    target: jax.Array,
    from_logits: bool,
    num_tasks: int,
    weight: Optional[jax.Array] = None,
) -> None:
    if input.shape != target.shape:
        raise ValueError(
            f"`input` shape ({input.shape}) is different from `target` shape "
            f"({target.shape})"
        )
    if weight is not None and input.shape != weight.shape:
        raise ValueError(
            f"`weight` shape ({weight.shape}) is different from `input` shape "
            f"({input.shape})"
        )
    check_task_shape(input, num_tasks)


@partial(jax.jit, static_argnames=("from_logits",))
def _ne_fold(
    input: jax.Array,
    target: jax.Array,
    from_logits: bool,
    weight: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    input = input.astype(jnp.float32)
    target = target.astype(jnp.float32)
    if from_logits:
        # stable BCE-with-logits: softplus(x) - x*z
        ce = jax.nn.softplus(input) - input * target
    else:
        # torch.binary_cross_entropy clamps log terms at -100
        ce = -(
            target * jnp.clip(jnp.log(input), -100.0)
            + (1.0 - target) * jnp.clip(jnp.log1p(-input), -100.0)
        )
    if weight is not None:
        ce = ce * weight
        w = weight.astype(jnp.float32)
    else:
        w = jnp.ones_like(target)
    cross_entropy = jnp.sum(ce, axis=-1)
    num_examples = jnp.sum(w, axis=-1)
    num_positive = jnp.sum(w * target, axis=-1)
    return cross_entropy, num_positive, num_examples


def _binary_normalized_entropy_update(
    input: jax.Array,
    target: jax.Array,
    from_logits: bool,
    num_tasks: int,
    weight: Optional[jax.Array] = None,
    *,
    value_check_source=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    _ne_input_check(input, target, from_logits, num_tasks, weight)
    # check values against the RAW pre-placement input when given: by now
    # ``input`` is device-placed even if the caller passed numpy
    _ne_value_check(
        value_check_source if value_check_source is not None else input,
        from_logits,
    )
    return _ne_fold(input, target, from_logits, weight)


@jax.jit
def _baseline_entropy(num_positive: jax.Array, num_examples: jax.Array) -> jax.Array:
    p = jnp.clip(num_positive / num_examples, _EPS, 1.0 - _EPS)
    return -p * jnp.log(p) - (1.0 - p) * jnp.log(1.0 - p)


def binary_normalized_entropy(
    input,
    target,
    *,
    weight=None,
    num_tasks: int = 1,
    from_logits: bool = False,
) -> jax.Array:
    """Normalized binary cross entropy: observed CE over base-rate entropy.

    Args:
        input: probabilities (or logits with ``from_logits=True``),
            shape ``(num_samples,)`` or ``(num_tasks, num_samples)``.
        target: binary labels, same shape.
        weight: optional rescaling weights, same shape.
        num_tasks: number of parallel tasks (leading axis when > 1).
        from_logits: interpret ``input`` as logits.
    """
    raw_input = input
    input, target = as_jax(input), as_jax(target)
    if weight is not None:
        weight = as_jax(weight)
    cross_entropy, num_positive, num_examples = _binary_normalized_entropy_update(
        input, target, from_logits, num_tasks, weight,
        value_check_source=raw_input,
    )
    return (cross_entropy / num_examples) / _baseline_entropy(
        num_positive, num_examples
    )

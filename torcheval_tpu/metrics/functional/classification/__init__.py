from torcheval_tpu.metrics.functional.classification.accuracy import (
    binary_accuracy,
    multiclass_accuracy,
    multilabel_accuracy,
    topk_multilabel_accuracy,
)
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
)
from torcheval_tpu.metrics.functional.classification.f1_score import (
    binary_f1_score,
    multiclass_f1_score,
)
from torcheval_tpu.metrics.functional.classification.precision import (
    binary_precision,
    multiclass_precision,
)
from torcheval_tpu.metrics.functional.classification.recall import (
    binary_recall,
    multiclass_recall,
)

__all__ = [
    "binary_accuracy",
    "binary_confusion_matrix",
    "binary_f1_score",
    "binary_precision",
    "binary_recall",
    "multiclass_accuracy",
    "multiclass_confusion_matrix",
    "multiclass_f1_score",
    "multiclass_precision",
    "multiclass_recall",
    "multilabel_accuracy",
    "topk_multilabel_accuracy",
]

"""Binned precision-recall curves (static-shape streaming PRC). Reference:
``torcheval/metrics/functional/classification/binned_precision_recall_curve.py``.

This is the TPU hot path for PR curves: counter state of shape
``(n_thresholds,)`` / ``(n_thresholds, num_classes)``, fixed at trace time,
SUM-mergeable, so the streaming update is one fused compare-and-reduce kernel
and distributed sync is a single ``psum``.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check,
    _multiclass_precision_recall_curve_update_input_check,
)
from torcheval_tpu.utils.convert import as_jax

ThresholdSpec = Union[int, List[float], jax.Array]


def _create_threshold_tensor(threshold: ThresholdSpec) -> jax.Array:
    if isinstance(threshold, int):
        return jnp.linspace(0.0, 1.0, threshold)
    return as_jax(threshold)


def _binned_precision_recall_curve_param_check(threshold: jax.Array) -> None:
    import numpy as np

    t = np.asarray(threshold)
    if (np.diff(t) < 0.0).any():
        raise ValueError("The `threshold` should be a sorted array.")
    if (t < 0.0).any() or (t > 1.0).any():
        raise ValueError("The values in `threshold` should be in the range of [0, 1].")


@jax.jit
def _binary_binned_update(
    input: jax.Array, target: jax.Array, threshold: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    target = target.astype(jnp.int32)
    pred_label = input[None, :] >= threshold[:, None]  # (T, N)
    num_tp = jnp.sum(pred_label * target[None, :], axis=1, dtype=jnp.int32)
    num_fp = jnp.sum(pred_label, axis=1, dtype=jnp.int32) - num_tp
    num_fn = jnp.sum(target, dtype=jnp.int32) - num_tp
    return num_tp, num_fp, num_fn


@jax.jit
def _binary_binned_compute(
    num_tp: jax.Array, num_fp: jax.Array, num_fn: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    tp = num_tp.astype(jnp.float32)
    fp = num_fp.astype(jnp.float32)
    fn = num_fn.astype(jnp.float32)
    # precision 1.0 when nothing is predicted positive (reference nan_to_num)
    precision = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 1.0)
    recall = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1.0), jnp.nan)
    precision = jnp.concatenate([precision, jnp.ones(1)])
    recall = jnp.concatenate([recall, jnp.zeros(1)])
    return precision, recall


def binary_binned_precision_recall_curve(
    input, target, *, threshold: ThresholdSpec = 100
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Precision-recall curve at fixed thresholds (binary).

    Args:
        input: probabilities / logits, shape ``(n_sample,)``.
        target: binary labels, shape ``(n_sample,)``.
        threshold: bin count (int → ``linspace(0, 1)``), list, or array of
            sorted thresholds in ``[0, 1]``.

    Returns:
        ``(precision, recall, thresholds)`` of shapes
        ``(T+1,), (T+1,), (T,)``.
    """
    input, target = as_jax(input), as_jax(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    _binary_precision_recall_curve_update_input_check(input, target)
    num_tp, num_fp, num_fn = _binary_binned_update(input, target, threshold)
    precision, recall = _binary_binned_compute(num_tp, num_fp, num_fn)
    return precision, recall, threshold


@partial(jax.jit, static_argnames=("num_classes",))
def _multiclass_binned_update(
    input: jax.Array, target: jax.Array, threshold: jax.Array, num_classes: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    onehot = (
        target[:, None] == jnp.arange(num_classes)[None, :]
    ).astype(jnp.int32)  # (N, C)
    labels = (
        input[None, :, :] >= threshold[:, None, None]
    )  # (T, N, C) — one compare+reduce pass, XLA fuses the broadcast
    num_tp = jnp.sum(labels * onehot[None, :, :], axis=1, dtype=jnp.int32)
    num_fp = jnp.sum(labels, axis=1, dtype=jnp.int32) - num_tp
    num_fn = jnp.sum(onehot, axis=0, dtype=jnp.int32)[None, :] - num_tp
    return num_tp, num_fp, num_fn


@jax.jit
def _multiclass_binned_compute(
    num_tp: jax.Array, num_fp: jax.Array, num_fn: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    tp = num_tp.astype(jnp.float32)
    fp = num_fp.astype(jnp.float32)
    fn = num_fn.astype(jnp.float32)
    precision = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 1.0)
    recall = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1.0), jnp.nan)
    num_classes = tp.shape[1]
    precision = jnp.concatenate([precision, jnp.ones((1, num_classes))], axis=0)
    recall = jnp.concatenate([recall, jnp.zeros((1, num_classes))], axis=0)
    return precision, recall


def multiclass_binned_precision_recall_curve(
    input,
    target,
    *,
    num_classes: Optional[int] = None,
    threshold: ThresholdSpec = 100,
) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
    """One-vs-all precision-recall curves at fixed thresholds.

    Args:
        input: scores/logits ``(n_sample, num_classes)``.
        target: class indices ``(n_sample,)``.
        num_classes: defaults to ``input.shape[1]``.
        threshold: bin count, list, or array of sorted thresholds in [0, 1].

    Returns:
        ``(precision, recall, thresholds)`` — precision/recall are lists with
        one ``(T+1,)`` array per class (reference layout).
    """
    input, target = as_jax(input), as_jax(target)
    threshold = _create_threshold_tensor(threshold)
    _binned_precision_recall_curve_param_check(threshold)
    if num_classes is None and input.ndim == 2:
        num_classes = input.shape[1]
    _multiclass_precision_recall_curve_update_input_check(input, target, num_classes)
    num_tp, num_fp, num_fn = _multiclass_binned_update(
        input, target, threshold, num_classes
    )
    precision, recall = _multiclass_binned_compute(num_tp, num_fp, num_fn)
    return list(precision.T), list(recall.T), threshold

"""Accuracy family: multiclass / binary / multilabel / top-k multilabel.

Reference semantics: ``torcheval/metrics/functional/classification/accuracy.py``
(update math at ``:246-432``). TPU re-design notes:

* per-class counts go through :func:`torcheval_tpu.ops.class_counts`
  (one-hot-matmul / scatter auto-pick) instead of ``Tensor.scatter_``;
* macro averaging is computed with full-width masks (``jnp.where``), never
  boolean fancy-indexing — shapes stay static under jit;
* counters are int32 (exact to 2.1e9 samples; the reference's float scatter
  loses integer exactness past 16.7M);
* the reference's hardcoded ``topk(k=2)`` bug (``accuracy.py:394`` ignores
  ``self.k``) is fixed here: ``k`` is respected.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.ops.confusion import class_counts
from torcheval_tpu.utils.convert import as_jax

_AVERAGE_OPTIONS = ("micro", "macro", "none", None)
_CRITERIA_OPTIONS = ("exact_match", "hamming", "overlap", "contain", "belong")


# --------------------------------------------------------------------- checks
def _accuracy_param_check(
    average: Optional[str], num_classes: Optional[int], k: int = 1
) -> None:
    if average not in _AVERAGE_OPTIONS:
        raise ValueError(
            f"`average` was not in the allowed value of {_AVERAGE_OPTIONS}, got {average}."
        )
    if average != "micro" and (num_classes is None or num_classes <= 0):
        raise ValueError(
            f"num_classes should be a positive number when average={average}."
            f" Got num_classes={num_classes}."
        )
    if type(k) is not int:
        raise TypeError(f"Expected `k` to be an integer, but {type(k)} was provided.")
    if k < 1:
        raise ValueError(
            f"Expected `k` to be an integer greater than 0, but {k} was provided."
        )


def _accuracy_update_input_check(
    input: jax.Array, target: jax.Array, num_classes: Optional[int], k: int
) -> None:
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "The `input` and `target` should have the same first dimension, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if k > 1 and input.ndim != 2:
        raise ValueError(
            "input should have shape (num_sample, num_classes) for k > 1, "
            f"got shape {input.shape}."
        )
    if not input.ndim == 1 and not (
        input.ndim == 2 and (num_classes is None or input.shape[1] == num_classes)
    ):
        raise ValueError(
            "input should have shape of (num_sample,) or (num_sample, num_classes), "
            f"got {input.shape}."
        )


# -------------------------------------------------------------------- kernels
@partial(jax.jit, static_argnames=("average", "num_classes", "k"))
def _multiclass_accuracy_update(
    input: jax.Array,
    target: jax.Array,
    average: Optional[str],
    num_classes: Optional[int],
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    if k == 1:
        if input.ndim == 2:
            input = jnp.argmax(input, axis=1)
        mask = (input == target).astype(jnp.int32)
    else:
        y_score = jnp.take_along_axis(input, target[:, None].astype(jnp.int32), axis=-1)
        rank = jnp.sum(input > y_score, axis=-1)
        mask = (rank < k).astype(jnp.int32)

    if average == "micro":
        return mask.sum(), jnp.asarray(target.shape[0], dtype=jnp.int32)

    num_correct = class_counts(target.astype(jnp.int32), num_classes, mask)
    num_total = class_counts(target.astype(jnp.int32), num_classes)
    return num_correct, num_total


@partial(jax.jit, static_argnames=("average",))
def _accuracy_compute(
    num_correct: jax.Array, num_total: jax.Array, average: Optional[str]
) -> jax.Array:
    num_correct = num_correct.astype(jnp.float32)
    num_total = num_total.astype(jnp.float32)
    if average == "macro":
        valid = num_total != 0
        per_class = jnp.where(valid, num_correct / jnp.maximum(num_total, 1.0), 0.0)
        return per_class.sum() / jnp.maximum(valid.sum(), 1)
    return num_correct / num_total


@jax.jit
def _binary_accuracy_update(
    input: jax.Array, target: jax.Array, threshold: float
) -> Tuple[jax.Array, jax.Array]:
    pred = jnp.where(input < threshold, 0, 1)
    num_correct = (pred == target).sum(dtype=jnp.int32)
    return num_correct, jnp.asarray(target.shape[0], dtype=jnp.int32)


@partial(jax.jit, static_argnames=("criteria",))
def _multilabel_update(
    input_label: jax.Array, target: jax.Array, criteria: str
) -> Tuple[jax.Array, jax.Array]:
    n = jnp.asarray(target.shape[0], dtype=jnp.int32)
    if criteria == "exact_match":
        return jnp.all(input_label == target, axis=1).sum(dtype=jnp.int32), n
    if criteria == "hamming":
        return (
            (input_label == target).sum(dtype=jnp.int32),
            jnp.asarray(target.size, dtype=jnp.int32),
        )
    if criteria == "overlap":
        hit = jnp.max(
            jnp.logical_and(input_label == target, input_label == 1), axis=1
        ).sum(dtype=jnp.int32)
        both_empty = jnp.all(
            jnp.logical_and(input_label == 0, target == 0), axis=1
        ).sum(dtype=jnp.int32)
        return hit + both_empty, n
    if criteria == "contain":
        return jnp.all(input_label - target >= 0, axis=1).sum(dtype=jnp.int32), n
    # belong
    return jnp.all(input_label - target <= 0, axis=1).sum(dtype=jnp.int32), n


def _multilabel_accuracy_param_check(criteria: str) -> None:
    if criteria not in _CRITERIA_OPTIONS:
        raise ValueError(
            f"`criteria` was not in the allowed value of {_CRITERIA_OPTIONS}, got {criteria}."
        )


def _multilabel_shape_check(input: jax.Array, target: jax.Array) -> None:
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same dimensions, "
            f"got shapes {input.shape} and {target.shape}."
        )


def _topk_multilabel_accuracy_param_check(criteria: str, k: int) -> None:
    _multilabel_accuracy_param_check(criteria)
    if type(k) is not int:
        raise TypeError(f"Expected `k` to be an integer, but {type(k)} was provided.")
    if k <= 1:
        raise ValueError(
            f"Expected `k` to be an integer greater than 1, but {k} was provided. "
            "For k = 1, please use multilabel_accuracy."
        )


def _multilabel_accuracy_update(
    input: jax.Array, target: jax.Array, threshold: float, criteria: str
) -> Tuple[jax.Array, jax.Array]:
    _multilabel_shape_check(input, target)
    input_label = jnp.where(input < threshold, 0, 1)
    return _multilabel_update(input_label, target, criteria)


@partial(jax.jit, static_argnames=("criteria", "k", "topk_method"))
def _topk_multilabel_stats(
    input: jax.Array,
    target: jax.Array,
    criteria: str,
    k: int,
    topk_method: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """All five criteria from set statistics, never materialising the (N, C)
    top-k one-hot (which costs seconds at num_labels=10k — BASELINE config 4).

    With ``P`` the k-element top-k set and ``T`` the positive-label set:
    ``inter = |P ∩ T|`` comes from gathering target values at the top-k
    indices; then exact_match ⇔ inter==k==|T|, hamming agreement =
    C - (k + |T| - 2·inter), overlap ⇔ inter>0 (P is never empty for k≥2),
    contain ⇔ T ⊆ P ⇔ inter==|T|, belong ⇔ P ⊆ T ⇔ inter==k.

    The top-k indices come from the streaming selection engine
    (``ops/topk.py``): at this kernel's hot sizes (config 4: L=10k ≫ the
    engine's ``_DENSE_L_MAX=1024`` dense threshold) ``auto`` routes to the
    Pallas VMEM streaming kernel on TPU and the threshold-prune lowering
    elsewhere, with identical values and tie-broken indices to the old
    full-sort ``lax.top_k``; ``topk_method`` forces a path (the bench A/B
    and the CPU suite's interpret-mode runs use it).
    """
    from torcheval_tpu.ops.topk import topk_indices

    idx = topk_indices(input, k, method=topk_method)
    tgt = (target != 0).astype(jnp.int32)
    inter = jnp.take_along_axis(tgt, idx, axis=1).sum(axis=1, dtype=jnp.int32)
    t_count = tgt.sum(axis=1, dtype=jnp.int32)
    n = jnp.asarray(target.shape[0], dtype=jnp.int32)
    num_classes = target.shape[1]
    if criteria == "exact_match":
        correct = ((inter == k) & (t_count == k)).sum(dtype=jnp.int32)
    elif criteria == "hamming":
        agree = num_classes - (k + t_count - 2 * inter)
        return agree.sum(dtype=jnp.int32), jnp.asarray(
            target.size, dtype=jnp.int32
        )
    elif criteria == "overlap":
        correct = (inter > 0).sum(dtype=jnp.int32)
    elif criteria == "contain":
        correct = (inter == t_count).sum(dtype=jnp.int32)
    else:  # belong
        correct = (inter == k).sum(dtype=jnp.int32)
    return correct, n


def _topk_multilabel_accuracy_update(
    input: jax.Array,
    target: jax.Array,
    criteria: str,
    k: int,
    topk_method: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    _multilabel_shape_check(input, target)
    if input.ndim != 2:
        raise ValueError(
            "input should have shape (num_sample, num_classes) for k > 1, "
            f"got shape {input.shape}."
        )
    # respects k (the reference hardcodes topk(k=2), accuracy.py:394)
    return _topk_multilabel_stats(input, target, criteria, k, topk_method)


# ----------------------------------------------------------------- public API
def multiclass_accuracy(
    input,
    target,
    *,
    average: Optional[str] = "micro",
    num_classes: Optional[int] = None,
    k: int = 1,
) -> jax.Array:
    """Frequency of predictions matching labels.

    Reference: ``functional/classification/accuracy.py:49-104``.

    Args:
        input: predicted labels ``(n_sample,)`` or probabilities/logits
            ``(n_sample, n_class)`` (argmax or top-k rank applied).
        target: ground-truth labels ``(n_sample,)``.
        average: ``"micro"`` (global), ``"macro"`` (unweighted class mean over
            classes seen in target), ``"none"``/``None`` (per-class vector).
        num_classes: required unless average is ``"micro"``.
        k: prediction counts as correct if the label ranks in the top k scores.
    """
    _accuracy_param_check(average, num_classes, k)
    input, target = as_jax(input), as_jax(target)
    _accuracy_update_input_check(input, target, num_classes, k)
    num_correct, num_total = _multiclass_accuracy_update(
        input, target, average, num_classes, k
    )
    return _accuracy_compute(num_correct, num_total, average)


def binary_accuracy(input, target, *, threshold: float = 0.5) -> jax.Array:
    """Binary accuracy after thresholding ``input``.

    Reference: ``functional/classification/accuracy.py:13-46``.
    """
    input, target = as_jax(input), as_jax(target)
    _multilabel_shape_check(input, target)
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    num_correct, num_total = _binary_accuracy_update(input, target, threshold)
    return _accuracy_compute(num_correct, num_total, "micro")


def multilabel_accuracy(
    input, target, *, threshold: float = 0.5, criteria: str = "exact_match"
) -> jax.Array:
    """Multilabel accuracy under one of five criteria
    (exact_match / hamming / overlap / contain / belong).

    Reference: ``functional/classification/accuracy.py:107-174``.
    """
    _multilabel_accuracy_param_check(criteria)
    input, target = as_jax(input), as_jax(target)
    num_correct, num_total = _multilabel_accuracy_update(
        input, target, threshold, criteria
    )
    return _accuracy_compute(num_correct, num_total, "micro")


def topk_multilabel_accuracy(
    input,
    target,
    *,
    criteria: str = "exact_match",
    k: int = 2,
    topk_method: str = "auto",
) -> jax.Array:
    """Multilabel accuracy where the prediction set is the top-k scores.

    Reference: ``functional/classification/accuracy.py:177-243`` — with the
    hardcoded ``topk(k=2)`` bug (``:394``) fixed to honour ``k``.

    ``topk_method`` forces a selection-engine lowering
    (``"dense"``/``"prune"``/``"pallas"``, see ``ops/topk.py``); the default
    ``"auto"`` picks by size and backend with identical results.
    """
    _topk_multilabel_accuracy_param_check(criteria, k)
    input, target = as_jax(input), as_jax(target)
    num_correct, num_total = _topk_multilabel_accuracy_update(
        input, target, criteria, k, topk_method
    )
    return _accuracy_compute(num_correct, num_total, "micro")

from torcheval_tpu.metrics.functional.regression.mean_squared_error import (
    mean_squared_error,
)
from torcheval_tpu.metrics.functional.regression.r2_score import r2_score

__all__ = ["mean_squared_error", "r2_score"]

"""R-squared score. Reference:
``torcheval/metrics/functional/regression/r2_score.py``.

Streaming form via four sufficient statistics per output —
``sum(y^2), sum(y), sum((y - yhat)^2), n`` — all SUM-mergeable, so the
distributed sync is one ``psum`` over a four-leaf pytree. TSS is recovered at
compute as ``sum(y^2) - sum(y)^2 / n`` (single-pass variance identity).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax


def _r2_score_param_check(multioutput: str, num_regressors: int) -> None:
    if multioutput not in ("raw_values", "uniform_average", "variance_weighted"):
        raise ValueError(
            "The `multioutput` must be either `raw_values` or `uniform_average` "
            f"or `variance_weighted`, got multioutput={multioutput}."
        )
    if not isinstance(num_regressors, int) or num_regressors < 0:
        raise ValueError(
            "The `num_regressors` must an integer larger or equal to zero, "
            f"got num_regressors={num_regressors}."
        )


def _r2_score_update_input_check(input: jax.Array, target: jax.Array) -> None:
    if input.ndim >= 3 or target.ndim >= 3:
        raise ValueError(
            "The dimension `input` and `target` should be 1D or 2D, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same size, "
            f"got shapes {input.shape} and {target.shape}."
        )


@jax.jit
def _r2_fold(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    target = target.astype(jnp.float32)
    input = input.astype(jnp.float32)
    sum_squared_obs = jnp.sum(jnp.square(target), axis=0)
    sum_obs = jnp.sum(target, axis=0)
    sum_squared_residual = jnp.sum(jnp.square(target - input), axis=0)
    # int32 count: exact to 2**31 samples (float32 would stall at 2**24)
    num_obs = jnp.asarray(target.shape[0], dtype=jnp.int32)
    return sum_squared_obs, sum_obs, sum_squared_residual, num_obs


def _r2_score_update(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    _r2_score_update_input_check(input, target)
    return _r2_fold(input, target)


def _r2_score_compute(
    sum_squared_obs: jax.Array,
    sum_obs: jax.Array,
    rss: jax.Array,
    num_obs: jax.Array,
    multioutput: str,
    num_regressors: int,
) -> jax.Array:
    n = float(num_obs)
    if n < 2:
        raise ValueError(
            "There is no enough data for computing. Needs at least two samples "
            "to calculate r2 score."
        )
    if num_regressors >= n - 1:
        raise ValueError(
            "The `num_regressors` must be smaller than n_samples - 1, "
            f"got num_regressors={num_regressors}, n_samples={n}.",
        )
    tss = sum_squared_obs - jnp.square(sum_obs) / num_obs
    r_squared = 1 - (rss / tss)
    if multioutput == "uniform_average":
        r_squared = jnp.mean(r_squared)
    elif multioutput == "variance_weighted":
        r_squared = jnp.sum(r_squared * tss / jnp.sum(tss))
    if num_regressors != 0:
        r_squared = 1 - (1 - r_squared) * (num_obs - 1) / (
            num_obs - num_regressors - 1
        )
    return r_squared


def r2_score(
    input,
    target,
    *,
    multioutput: str = "uniform_average",
    num_regressors: int = 0,
) -> jax.Array:
    """Compute the R-squared (coefficient of determination) score.

    Args:
        input: predicted values, shape ``(n_sample,)`` or ``(n_sample, n_output)``.
        target: ground truth, same shape as ``input``.
        multioutput: ``"uniform_average"``, ``"raw_values"``, or
            ``"variance_weighted"``.
        num_regressors: independent-variable count for adjusted R² (0 = plain R²).

    Reference parity: ``functional/regression/r2_score.py:14-160``.
    """
    _r2_score_param_check(multioutput, num_regressors)
    input, target = as_jax(input), as_jax(target)
    sum_squared_obs, sum_obs, sum_squared_residual, num_obs = _r2_score_update(
        input, target
    )
    return _r2_score_compute(
        sum_squared_obs,
        sum_obs,
        sum_squared_residual,
        num_obs,
        multioutput,
        num_regressors,
    )

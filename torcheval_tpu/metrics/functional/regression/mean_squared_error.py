"""Mean squared error. Reference:
``torcheval/metrics/functional/regression/mean_squared_error.py``.

Sufficient statistics are a per-output ``sum_squared_error`` and a scalar
``sum_weight`` — both SUM-mergeable, so distributed sync is a single ``psum``.
The batch fold is one fused XLA kernel (subtract/square/weighted-reduce);
no intermediate ever leaves HBM.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax


def _mean_squared_error_param_check(multioutput: str) -> None:
    if multioutput not in ("raw_values", "uniform_average"):
        raise ValueError(
            "The `multioutput` must be either `raw_values` or `uniform_average`, "
            f"got multioutput={multioutput}."
        )


def _mean_squared_error_update_input_check(
    input: jax.Array,
    target: jax.Array,
    sample_weight: Optional[jax.Array],
) -> None:
    if input.ndim >= 3 or target.ndim >= 3:
        raise ValueError(
            "The dimension `input` and `target` should be 1D or 2D, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if input.shape != target.shape:
        raise ValueError(
            "The `input` and `target` should have the same size, "
            f"got shapes {input.shape} and {target.shape}."
        )
    if sample_weight is not None:
        # the documented shape is (n_sample,); a 2-D weight would silently
        # mis-broadcast (n, d) * (n, 1, d) in the weighted fold (torch raises
        # a broadcast error for the same input — parity, but eager)
        if sample_weight.ndim != 1:
            raise ValueError(
                "The `sample_weight` should be a one-dimensional tensor of "
                f"shape (n_sample,), got shape {sample_weight.shape}."
            )
        if target.shape[0] != sample_weight.shape[0]:
            raise ValueError(
                "The first dimension of `input`, `target` and `sample_weight` should "
                f"be the same size, got shapes {input.shape}, {target.shape} and "
                f"{sample_weight.shape}."
            )


@jax.jit
def _mse_fold(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    squared_error = jnp.square(target.astype(jnp.float32) - input.astype(jnp.float32))
    sum_squared_error = jnp.sum(squared_error, axis=0)
    # int32 count: exact to 2**31 samples, where a float32 accumulator would
    # silently stall at 2**24 (ops/confusion.py applies the same rule)
    sum_weight = jnp.asarray(target.shape[0], dtype=jnp.int32)
    return sum_squared_error, sum_weight


@jax.jit
def _mse_fold_weighted(
    input: jax.Array, target: jax.Array, sample_weight: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    squared_error = jnp.square(target.astype(jnp.float32) - input.astype(jnp.float32))
    w = sample_weight.astype(jnp.float32)
    if squared_error.ndim == 2:
        squared_error = squared_error * w[:, None]
    else:
        squared_error = squared_error * w
    return jnp.sum(squared_error, axis=0), jnp.sum(w)


def _mean_squared_error_update(
    input: jax.Array,
    target: jax.Array,
    sample_weight: Optional[jax.Array],
) -> Tuple[jax.Array, jax.Array]:
    _mean_squared_error_update_input_check(input, target, sample_weight)
    if sample_weight is None:
        return _mse_fold(input, target)
    return _mse_fold_weighted(input, target, sample_weight)


def _mean_squared_error_compute(
    sum_squared_error: jax.Array,
    multioutput: str,
    sum_weight: jax.Array,
) -> jax.Array:
    raw_values = sum_squared_error / sum_weight
    if multioutput == "raw_values":
        return raw_values
    return jnp.mean(raw_values)


def mean_squared_error(
    input,
    target,
    *,
    sample_weight=None,
    multioutput: str = "uniform_average",
) -> jax.Array:
    """Compute mean squared error of ``input`` vs ``target``.

    Args:
        input: predicted values, shape ``(n_sample,)`` or ``(n_sample, n_output)``.
        target: ground truth, same shape as ``input``.
        sample_weight: optional per-sample weights, shape ``(n_sample,)``.
        multioutput: ``"uniform_average"`` (mean over outputs) or
            ``"raw_values"`` (per-output vector).

    Reference parity: ``functional/regression/mean_squared_error.py:13-110``.
    """
    _mean_squared_error_param_check(multioutput)
    input, target = as_jax(input), as_jax(target)
    if sample_weight is not None:
        sample_weight = as_jax(sample_weight)
    sum_squared_error, sum_weight = _mean_squared_error_update(
        input, target, sample_weight
    )
    return _mean_squared_error_compute(sum_squared_error, multioutput, sum_weight)

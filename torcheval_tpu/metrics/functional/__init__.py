from torcheval_tpu.metrics.functional.aggregation import mean, sum  # noqa: A004

__all__ = [
    "mean",
    "sum",
]

from torcheval_tpu.metrics.functional.aggregation import mean, sum  # noqa: A004
from torcheval_tpu.metrics.functional.classification import (
    binary_accuracy,
    binary_confusion_matrix,
    binary_f1_score,
    binary_precision,
    binary_recall,
    multiclass_accuracy,
    multiclass_confusion_matrix,
    multiclass_f1_score,
    multiclass_precision,
    multiclass_recall,
    multilabel_accuracy,
    topk_multilabel_accuracy,
)
from torcheval_tpu.metrics.functional.ranking import (
    frequency_at_k,
    hit_rate,
    num_collisions,
    reciprocal_rank,
)
from torcheval_tpu.metrics.functional.regression import mean_squared_error, r2_score

__all__ = [
    "binary_accuracy",
    "binary_confusion_matrix",
    "binary_f1_score",
    "binary_precision",
    "binary_recall",
    "frequency_at_k",
    "hit_rate",
    "mean",
    "mean_squared_error",
    "multiclass_accuracy",
    "multiclass_confusion_matrix",
    "multiclass_f1_score",
    "multiclass_precision",
    "multiclass_recall",
    "multilabel_accuracy",
    "num_collisions",
    "r2_score",
    "reciprocal_rank",
    "sum",
    "topk_multilabel_accuracy",
]

"""Retrieval metric kernels @ k — NDCG, MAP, Recall, HitRate — over a
``(num_samples, num_labels)`` relevance matrix (ISSUE 14).

These are the extreme-vocabulary metrics (retrieval / recsys / LLM-head
eval, L ~ 10⁶–10⁸): every kernel reduces the label axis through the
streaming top-k engine (``ops/topk.py``), never a full-width sort, and the
relevance gather rides the engine too. Two label-axis regimes, one math:

* single-device: ``topk(...)`` picks the Pallas VMEM streaming kernel /
  dense / prune lowering per size and backend; the relevance gather is a
  local ``take_along_axis`` at the selected indices.
* label-sharded (``label_mesh=(mesh, axis_name)``): the block-distributed
  engine (``sharded_label_topk``) runs the per-shard kernel and gathers the
  relevance INSIDE each shard, so neither the score nor the relevance
  matrix is ever replicated — the only cross-shard traffic is the
  O(k·shards) candidate exchange.

Per-sample semantics (the numpy-oracle contract pinned in
``tests/metrics/test_retrieval.py``):

* a row is VALID when it has at least one relevant label (``target > 0``;
  for NDCG: a positive ideal DCG). Invalid rows return NaN — the
  ``hit_rate`` NaN-poison convention — and the class metrics exclude them
  from the mean.
* ``recall_at_k``: ``|top-k ∩ relevant| / |relevant|``.
* ``map_at_k``: ``(1 / min(|relevant|, k)) · Σ_j rel_j · precision@j`` —
  the standard truncated average precision.
* ``ndcg_at_k``: graded relevance, linear gains, ``1/log2(rank+2)``
  discounts; the ideal ordering is the top-k of the relevance row itself
  (computed through the same engine, so a label-sharded relevance matrix
  stays sharded).
* ``retrieval_hit_rate``: 1.0 iff any relevant label ranks in the top-k.
  For single-label (one-hot) targets and tie-free scores this agrees
  per-sample with :func:`~torcheval_tpu.metrics.functional.hit_rate` — the
  k-parametrized alignment the test suite pins.

Tie discipline: ranks come from the engine's ``lax.top_k``-exact order
(values descending, ties by lowest global index), so every kernel is
deterministic and bit-stable across the dense, pallas, prune and
label-sharded paths.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax


def _retrieval_input_check(
    input: jax.Array, target: jax.Array, k: Optional[int]
) -> None:
    if input.ndim != 2:
        raise ValueError(
            f"input should be a two-dimensional tensor, got shape {input.shape}."
        )
    if target.shape != input.shape:
        raise ValueError(
            "`input` and `target` should have the same (num_samples, "
            f"num_labels) shape, got {input.shape} and {target.shape}."
        )
    if k is not None and (type(k) is not int or k <= 0):
        raise ValueError(f"k should be None or a positive int, got {k!r}.")


def _check_label_mesh(label_mesh) -> None:
    """Eager validation of the ``label_mesh`` knob: ``(mesh,
    label_axis_name)`` or ``(mesh, label_axis_name, batch_axes)`` — the
    3-tuple threads the ROW sharding through to the shard_map on
    batch × label meshes (inside a jitted fold the operand is a tracer, so
    the engine cannot derive it). Axis names must exist on the mesh NOW: a
    typo must reject at construction, not as a KeyError at window close
    after the stream was accepted."""
    if label_mesh is None:
        return
    if (
        not isinstance(label_mesh, tuple)
        or len(label_mesh) not in (2, 3)
        or not isinstance(label_mesh[1], str)
    ):
        raise ValueError(
            "label_mesh must be a (Mesh, label_axis_name) or (Mesh, "
            f"label_axis_name, batch_axes) tuple, got {label_mesh!r}."
        )
    mesh, label_axis = label_mesh[0], label_mesh[1]
    axes = tuple(getattr(mesh, "shape", {}) or ())
    if label_axis not in axes:
        raise ValueError(
            f"label_mesh names label axis {label_axis!r}, which is not an "
            f"axis of the mesh (axes: {axes})."
        )
    if len(label_mesh) == 3 and label_mesh[2] is not None:
        batch = label_mesh[2]
        batch_axes = batch if isinstance(batch, tuple) else (batch,)
        for a in batch_axes:
            if a not in axes or a == label_axis:
                raise ValueError(
                    f"label_mesh batch axes {batch!r} must name mesh axes "
                    f"distinct from the label axis (axes: {axes})."
                )


def _label_mesh_parts(label_mesh):
    """``(mesh, label_axis, batch_axes)`` from a validated 2- or 3-tuple."""
    mesh, axis = label_mesh[0], label_mesh[1]
    batch = label_mesh[2] if len(label_mesh) == 3 else None
    return mesh, axis, batch


def _topk_rel(
    input: jax.Array,
    target: jax.Array,
    k: int,
    topk_method: str,
    label_mesh,
) -> jax.Array:
    """Relevance gathered at the top-k score positions, ``(N, k)``, in the
    engine's exact rank order."""
    from torcheval_tpu.ops.topk import sharded_label_topk, topk

    if label_mesh is not None:
        mesh, axis, batch = _label_mesh_parts(label_mesh)
        _v, _i, rel = sharded_label_topk(
            input, k, mesh=mesh, label_axis=axis, batch_axes=batch,
            method=topk_method, gather=target.astype(jnp.float32),
        )
        return rel
    _v, idx = topk(input, k, method=topk_method)
    return jnp.take_along_axis(target.astype(jnp.float32), idx, axis=1)


def _ideal_topk(target: jax.Array, k: int, topk_method: str, label_mesh):
    """Top-k of the relevance row itself (the ideal ordering), through the
    same engine so a sharded relevance matrix stays sharded."""
    from torcheval_tpu.ops.topk import sharded_label_topk, topk

    t = target.astype(jnp.float32)
    if label_mesh is not None:
        mesh, axis, batch = _label_mesh_parts(label_mesh)
        return sharded_label_topk(
            t, k, mesh=mesh, label_axis=axis, batch_axes=batch,
            method=topk_method,
        )[0]
    return topk(t, k, method=topk_method)[0]


def _num_relevant(target: jax.Array) -> jax.Array:
    """Per-row relevant-label count — a label-axis sum, which GSPMD reduces
    with one tiny all-reduce on a sharded target (never a gather)."""
    return jnp.sum((target > 0).astype(jnp.float32), axis=1)


def _resolve_k(k: Optional[int], num_labels: int) -> int:
    return num_labels if k is None else min(k, num_labels)


_KERNEL_STATICS = ("k", "topk_method", "label_mesh")


@partial(jax.jit, static_argnames=_KERNEL_STATICS)
def _recall_kernel(input, target, k, topk_method, label_mesh):
    k = _resolve_k(k, input.shape[1])
    hits = jnp.sum(
        (_topk_rel(input, target, k, topk_method, label_mesh) > 0).astype(
            jnp.float32
        ),
        axis=1,
    )
    m = _num_relevant(target)
    return jnp.where(m > 0, hits / jnp.maximum(m, 1.0), jnp.nan)


@partial(jax.jit, static_argnames=_KERNEL_STATICS)
def _map_kernel(input, target, k, topk_method, label_mesh):
    k = _resolve_k(k, input.shape[1])
    rel = (_topk_rel(input, target, k, topk_method, label_mesh) > 0).astype(
        jnp.float32
    )
    prec = jnp.cumsum(rel, axis=1) / jnp.arange(1, k + 1, dtype=jnp.float32)
    m = _num_relevant(target)
    denom = jnp.maximum(jnp.minimum(m, float(k)), 1.0)
    ap = jnp.sum(rel * prec, axis=1) / denom
    return jnp.where(m > 0, ap, jnp.nan)


@partial(jax.jit, static_argnames=_KERNEL_STATICS)
def _ndcg_kernel(input, target, k, topk_method, label_mesh):
    k = _resolve_k(k, input.shape[1])
    disc = 1.0 / jnp.log2(jnp.arange(k, dtype=jnp.float32) + 2.0)
    gains = _topk_rel(input, target, k, topk_method, label_mesh)
    dcg = jnp.sum(gains * disc, axis=1)
    ideal = _ideal_topk(target, k, topk_method, label_mesh)
    # ragged ideal rows (fewer than k relevant labels): the engine returns
    # the actual (possibly zero/negative-padded) relevance tail, which
    # contributes nothing for the standard non-negative graded targets
    idcg = jnp.sum(jnp.maximum(ideal, 0.0) * disc, axis=1)
    return jnp.where(idcg > 0, dcg / jnp.where(idcg > 0, idcg, 1.0), jnp.nan)


@partial(jax.jit, static_argnames=_KERNEL_STATICS)
def _hit_rate_kernel(input, target, k, topk_method, label_mesh):
    k = _resolve_k(k, input.shape[1])
    hit = jnp.max(
        (_topk_rel(input, target, k, topk_method, label_mesh) > 0).astype(
            jnp.float32
        ),
        axis=1,
    )
    m = _num_relevant(target)
    return jnp.where(m > 0, hit, jnp.nan)


def _entry(kernel, input, target, k, topk_method, label_mesh):
    input, target = as_jax(input), as_jax(target)
    _retrieval_input_check(input, target, k)
    _check_label_mesh(label_mesh)
    return kernel(input, target, k, topk_method, label_mesh)


def recall_at_k(
    input,
    target,
    *,
    k: Optional[int] = None,
    topk_method: str = "auto",
    label_mesh: Optional[Tuple] = None,
) -> jax.Array:
    """Per-sample Recall@k: relevant labels ranked in the top ``k`` over the
    row's relevant-label count (NaN for rows with no relevant label).

    Args:
        input: scores/logits ``(num_samples, num_labels)``.
        target: relevance ``(num_samples, num_labels)`` (``> 0`` = relevant).
        k: cutoff; ``None`` (or ``k >= num_labels``) ranks every label.
        topk_method: streaming top-k engine lowering (``ops/topk.py``).
        label_mesh: optional ``(mesh, label_axis_name)`` — or ``(mesh,
            label_axis_name, batch_axes)`` on batch × label meshes —
            engaging the label-sharded engine (required inside jit, where
            operand shardings are invisible).
    """
    return _entry(_recall_kernel, input, target, k, topk_method, label_mesh)


def map_at_k(
    input,
    target,
    *,
    k: Optional[int] = None,
    topk_method: str = "auto",
    label_mesh: Optional[Tuple] = None,
) -> jax.Array:
    """Per-sample MAP@k (truncated average precision): ``(1/min(m, k)) ·
    Σ_j rel_j · precision@j`` with ``m`` the row's relevant count (NaN for
    rows with no relevant label). Arguments as :func:`recall_at_k`."""
    return _entry(_map_kernel, input, target, k, topk_method, label_mesh)


def ndcg_at_k(
    input,
    target,
    *,
    k: Optional[int] = None,
    topk_method: str = "auto",
    label_mesh: Optional[Tuple] = None,
) -> jax.Array:
    """Per-sample NDCG@k: linear graded gains, ``1/log2(rank+2)`` discounts,
    normalized by the row's ideal (relevance-sorted) DCG@k (NaN for rows
    whose ideal DCG is zero). Arguments as :func:`recall_at_k`."""
    return _entry(_ndcg_kernel, input, target, k, topk_method, label_mesh)


def retrieval_hit_rate(
    input,
    target,
    *,
    k: Optional[int] = None,
    topk_method: str = "auto",
    label_mesh: Optional[Tuple] = None,
) -> jax.Array:
    """Per-sample HitRate@k over a relevance matrix: 1.0 iff any relevant
    label ranks in the top ``k`` (NaN for rows with no relevant label).
    Agrees per-sample with the single-label
    :func:`~torcheval_tpu.metrics.functional.hit_rate` on one-hot targets
    with tie-free scores. Arguments as :func:`recall_at_k`."""
    return _entry(_hit_rate_kernel, input, target, k, topk_method, label_mesh)

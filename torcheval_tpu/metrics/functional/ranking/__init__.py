from torcheval_tpu.metrics.functional.ranking.frequency import frequency_at_k
from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics.functional.ranking.num_collisions import num_collisions
from torcheval_tpu.metrics.functional.ranking.reciprocal_rank import reciprocal_rank
from torcheval_tpu.metrics.functional.ranking.retrieval import (
    map_at_k,
    ndcg_at_k,
    recall_at_k,
    retrieval_hit_rate,
)

__all__ = [
    "frequency_at_k",
    "hit_rate",
    "map_at_k",
    "ndcg_at_k",
    "num_collisions",
    "recall_at_k",
    "reciprocal_rank",
    "retrieval_hit_rate",
]

from torcheval_tpu.metrics.functional.ranking.frequency import frequency_at_k
from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics.functional.ranking.num_collisions import num_collisions
from torcheval_tpu.metrics.functional.ranking.reciprocal_rank import reciprocal_rank

__all__ = [
    "frequency_at_k",
    "hit_rate",
    "num_collisions",
    "reciprocal_rank",
]

"""Frequency threshold indicator. Reference:
``torcheval/metrics/functional/ranking/frequency.py:13-43``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax


def _frequency_input_check(input: jax.Array, k: float) -> None:
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )
    if k < 0:
        raise ValueError(f"k should not be negative, got {k}.")


def frequency_at_k(input, k: float) -> jax.Array:
    """Binary indicator ``1.0`` where ``input < k`` (frequency below threshold).

    Args:
        input: 1-D frequencies.
        k: non-negative threshold.
    """
    input = as_jax(input)
    _frequency_input_check(input, k)
    return (input < k).astype(jnp.float32)

"""Hit rate @ k. Reference:
``torcheval/metrics/functional/ranking/hit_rate.py:13-67``.

The rank test gathers only the target's score and counts how many scores
strictly exceed it — O(N·C) elementwise compare + row reduce, no top-k sort
and no (N, k) gather, so XLA fuses it into one pass over the score matrix.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.tracing import is_concrete


def _target_range_check(input: jax.Array, target: jax.Array) -> None:
    """Reject out-of-range target indices, which ``take_along_axis`` would
    otherwise silently clamp (torch's ``gather`` raises — parity). Only runs
    on concrete arrays: inside jit the kernels NaN-poison invalid rows
    instead, keeping the traced path pure and sync-free."""
    if not is_concrete(target):
        return
    import numpy as np

    t = np.asarray(target)
    if t.size and (t.min() < 0 or t.max() >= input.shape[-1]):
        raise ValueError(
            f"target indices must be in [0, {input.shape[-1]}), got values in "
            f"[{t.min()}, {t.max()}]."
        )


def _hit_rate_input_check(
    input: jax.Array, target: jax.Array, k: Optional[int] = None
) -> None:
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 2:
        raise ValueError(
            f"input should be a two-dimensional tensor, got shape {input.shape}."
        )
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "`input` and `target` should have the same minibatch dimension, "
            f"got shapes {input.shape} and {target.shape}, respectively."
        )
    if k is not None and k <= 0:
        raise ValueError(f"k should be None or positive, got {k}.")


@partial(jax.jit, static_argnames=("k",))
def _hit_rate_kernel(input: jax.Array, target: jax.Array, k: int) -> jax.Array:
    target = target.astype(jnp.int32)
    y_score = jnp.take_along_axis(input, target[:, None], axis=-1)
    rank = jnp.sum(input > y_score, axis=-1)
    hit = (rank < k).astype(jnp.float32)
    valid = (target >= 0) & (target < input.shape[-1])
    return jnp.where(valid, hit, jnp.nan)


def hit_rate(input, target, *, k: Optional[int] = None) -> jax.Array:
    """Per-sample indicator of the target class ranking in the top ``k``.

    Args:
        input: scores/logits ``(num_samples, num_classes)``.
        target: class indices ``(num_samples,)``.
        k: top-k cutoff; ``None`` (or ``k >= num_classes``) hits everything.
    """
    input, target = as_jax(input), as_jax(target)
    _hit_rate_input_check(input, target, k)
    _target_range_check(input, target)
    if k is None or k >= input.shape[-1]:
        # same NaN-poisoning as the k-set kernel so invalid-target semantics
        # match between the two paths under tracing
        target = target.astype(jnp.int32)
        valid = (target >= 0) & (target < input.shape[-1])
        return jnp.where(valid, 1.0, jnp.nan).astype(jnp.float32)
    return _hit_rate_kernel(input, target, k)

"""Per-id collision counts. Reference:
``torcheval/metrics/functional/ranking/num_collisions.py:11-52``.

The reference materialises an (N, N) equality matrix — O(N²) memory
(``num_collisions.py:33-36``). The TPU kernel instead sorts once and binary-
searches each id against the sorted array: ``count(id) = right - left``,
O(N log N) compute, O(N) memory, all static-shape XLA ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torcheval_tpu.utils.convert import as_jax


def _num_collisions_input_check(input: jax.Array) -> None:
    if input.ndim != 1:
        raise ValueError(
            f"input should be a one-dimensional tensor, got shape {input.shape}."
        )
    if not jnp.issubdtype(input.dtype, jnp.integer):
        raise ValueError(f"input should be an integer tensor, got {input.dtype}.")


@jax.jit
def _num_collisions_kernel(input: jax.Array) -> jax.Array:
    sorted_ids = jnp.sort(input)
    left = jnp.searchsorted(sorted_ids, input, side="left")
    right = jnp.searchsorted(sorted_ids, input, side="right")
    return (right - left - 1).astype(jnp.int32)


def num_collisions(input) -> jax.Array:
    """For each id, the number of *other* occurrences of the same id.

    Args:
        input: 1-D integer ids ``(num_samples,)``.
    """
    input = as_jax(input)
    _num_collisions_input_check(input)
    return _num_collisions_kernel(input)

"""Reciprocal rank. Reference:
``torcheval/metrics/functional/ranking/reciprocal_rank.py:13-63``."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.ranking.hit_rate import _target_range_check
from torcheval_tpu.utils.convert import as_jax


def _reciprocal_rank_input_check(input: jax.Array, target: jax.Array) -> None:
    if target.ndim != 1:
        raise ValueError(
            f"target should be a one-dimensional tensor, got shape {target.shape}."
        )
    if input.ndim != 2:
        raise ValueError(
            f"input should be a two-dimensional tensor, got shape {input.shape}."
        )
    if input.shape[0] != target.shape[0]:
        raise ValueError(
            "`input` and `target` should have the same minibatch dimension, "
            f"got shapes {input.shape} and {target.shape}, respectively."
        )


@partial(jax.jit, static_argnames=("k",))
def _reciprocal_rank_kernel(
    input: jax.Array, target: jax.Array, k: Optional[int]
) -> jax.Array:
    from torcheval_tpu.ops.topk import _pick_method, topk_values

    target = target.astype(jnp.int32)
    y_score = jnp.take_along_axis(input, target[:, None], axis=-1)
    if (
        k is not None
        and k < input.shape[-1]
        and _pick_method(input.shape[-1], k, input.dtype, "auto") != "dense"
    ):
        # k-truncated path on the streaming top-k engine (ops/topk.py): only
        # ranks < k matter, and against the k largest VALUES the truncated
        # rank is exact — when the true rank r < k, all r elements above the
        # target score are among the top-k values, so the count matches; when
        # r >= k every top-k value beats the target and the count saturates
        # at k, exactly the cutoff bucket. Strict `>` keeps the reference's
        # tie semantics (equal scores never count against the target), so
        # this is bit-identical to the full-width comparison below.
        kv = topk_values(input.astype(jnp.float32), k)
        rank = jnp.sum(kv > y_score.astype(jnp.float32), axis=-1)
        score = jnp.where(rank >= k, 0.0, 1.0 / (rank.astype(jnp.float32) + 1.0))
    else:
        rank = jnp.sum(input > y_score, axis=-1)
        score = 1.0 / (rank.astype(jnp.float32) + 1.0)
        if k is not None:
            score = jnp.where(rank >= k, 0.0, score)
    valid = (target >= 0) & (target < input.shape[-1])
    return jnp.where(valid, score, jnp.nan)


def reciprocal_rank(input, target, *, k: Optional[int] = None) -> jax.Array:
    """Per-sample ``1 / (rank+1)`` of the target class; 0 beyond the ``k`` cutoff.

    Args:
        input: scores/logits ``(num_samples, num_classes)``.
        target: class indices ``(num_samples,)``.
        k: optional top-k cutoff.
    """
    input, target = as_jax(input), as_jax(target)
    _reciprocal_rank_input_check(input, target)
    _target_range_check(input, target)
    return _reciprocal_rank_kernel(input, target, k)

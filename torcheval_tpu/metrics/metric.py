"""The ``Metric`` base class: a thin stateful shell over a pure functional core.

Design (SURVEY.md §7). The reference's ``Metric`` is a mutating OO state
machine whose math runs eagerly under ``@torch.inference_mode()``
(``/root/reference/torcheval/metrics/metric.py:23-274``). The TPU-native
re-design keeps the same *protocol* — ``update / compute / merge_state /
reset / state_dict / load_state_dict / to`` — but:

* **State is a pytree of ``jax.Array``s** registered via :meth:`_add_state`,
  each with a declared :class:`~torcheval_tpu.metrics.state.Reduction` so the
  distributed toolkit can sync it with a typed XLA collective instead of
  pickling the object (reference: ``toolkit.py:235-257``).
* **All math lives in pure jitted kernels** under
  ``torcheval_tpu.metrics.functional``; class ``update`` methods only call a
  kernel and rebind the returned arrays. Nothing here blocks on device→host
  transfers, so back-to-back ``update()`` calls pipeline asynchronously on the
  TPU (JAX dispatch is async; only ``compute()`` materialises values).
* **No ``inference_mode`` analogue is needed** — JAX arrays are immutable and
  jitted kernels are pure by construction.

Class metrics exist for API parity with the reference; power users can drive
the pure kernels directly (``torcheval_tpu.metrics.functional``) or go through
the SPMD evaluator (``torcheval_tpu.parallel``).
"""

from __future__ import annotations

import copy
import logging
from abc import ABC, abstractmethod
from collections import defaultdict, deque
from typing import Any, Dict, Generic, Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.state import (
    Reduction,
    TState,
    check_state_type,
    copy_state,
    put_state,
)
from torcheval_tpu.obs.annotate import instrument_protocol
from torcheval_tpu.utils.devices import DeviceLike, canonical_device
from torcheval_tpu.utils.telemetry import log_api_usage_once

_logger: logging.Logger = logging.getLogger(__name__)

# Concrete device-array class for the hot-loop type checks: ``jax.Array`` is
# an ABC whose ``isinstance`` goes through ``_abc_instancecheck`` (~1.7 µs on
# the bench box — more than the rest of the update fast path combined);
# ``type(x) is ArrayImpl`` is a pointer compare (~40 ns) and excludes tracers
# for free (tracers are not ArrayImpl). Private import: on a jax that moved
# it, the sentinel ``None`` never matches a type and every call takes the
# (correct, slower) ABC path below.
try:
    from jax._src.array import ArrayImpl as _ARRAY_IMPL
except Exception:  # pragma: no cover - jax internals moved
    _ARRAY_IMPL = None


def _deepcopy_value(v: Any, memo: Dict[int, Any]) -> Any:
    """Deep copy for metric attributes that never routes a ``jax.Array``
    through ``copy.deepcopy``: Python's deepcopy of a device array does a
    host readback + re-upload (measured ~30 ms PER ARRAY on a tunneled chip
    vs 0.06 ms for a device-side ``jnp.copy``), and ``clone_metric`` — hence
    every explicit sync — deep-copies whole CAT caches. Array leaves go
    through ``_copy_leaf`` (device-side copy; alias when this process never
    donates). EXACT builtin container types recurse with full memo handling
    (identity sharing and cycles preserved, like ``copy.deepcopy``);
    subclasses (NamedTuple, Counter, ...) fall through to ``copy.deepcopy``
    so their type is preserved — only containers our own state machinery
    builds take the fast path. (``state.copy_state`` stays the copier for
    single STATE VALUES — flat TState containers with deque/defaultdict
    metadata; this walks whole attribute trees.)"""
    from torcheval_tpu.metrics.state import _copy_leaf

    if id(v) in memo:
        # consult the memo FIRST (arrays included) so two attributes that
        # reference the same object stay shared in the clone — deepcopy
        # identity semantics, which custom metrics may rely on
        return memo[id(v)]
    if isinstance(v, jax.Array):
        out = _copy_leaf(v)
        memo[id(v)] = out
        return out
    t = type(v)
    if t is list:
        out = []
        memo[id(v)] = out
        out.extend(_deepcopy_value(i, memo) for i in v)
        return out
    if t is tuple:
        out = tuple(_deepcopy_value(i, memo) for i in v)
        # setdefault, not assignment: a cycle through the tuple may have
        # memoized a copy during the recursion above; keep that one so the
        # cycle stays a single object (copy.deepcopy semantics)
        return memo.setdefault(id(v), out)
    if t is deque:
        out = deque(maxlen=v.maxlen)
        memo[id(v)] = out
        out.extend(_deepcopy_value(i, memo) for i in v)
        return out
    if t is defaultdict:
        out = defaultdict(v.default_factory)
        memo[id(v)] = out
        out.update({k: _deepcopy_value(x, memo) for k, x in v.items()})
        return out
    if t is dict:
        out = {}
        memo[id(v)] = out
        out.update({k: _deepcopy_value(x, memo) for k, x in v.items()})
        return out
    return copy.deepcopy(v, memo)


def _zero_scalar() -> jax.Array:
    """Module-level default factory so defaultdict state stays picklable."""
    return jnp.zeros(())

TComputeReturn = TypeVar("TComputeReturn")
TSelf = TypeVar("TSelf", bound="Metric")


class Metric(Generic[TComputeReturn], ABC):
    """Abstract streaming metric.

    Mirrors the reference protocol (``metric.py:23-274``): concrete metrics
    register state with :meth:`_add_state` and implement ``update``,
    ``compute`` and ``merge_state``. ``compute()`` must be idempotent and must
    not mutate state.
    """

    def __init_subclass__(cls, **kwargs: Any) -> None:
        # every concrete (and intermediate) metric class gets its protocol
        # methods annotated for the profiler/registry — per-class span names
        # like "metric.update/BinaryAUROC". Free while obs is disabled: the
        # wrapper is one module-global read, and scope annotation of traced
        # kernels costs only at trace time (obs/annotate.py).
        super().__init_subclass__(**kwargs)
        instrument_protocol(cls)

    def __init__(self, *, device: DeviceLike = None) -> None:
        # once-per-class usage telemetry, mirroring the reference's
        # torch._C._log_api_usage_once (metric.py:44) — a set lookup after
        # the first construction of each class, so the hot path stays flat
        log_api_usage_once(f"torcheval_tpu.metrics.{self.__class__.__name__}")
        self._bind_device(device)
        self._state_name_to_default: Dict[str, TState] = {}
        self._state_name_to_reduction: Dict[str, Reduction] = {}

    def _bind_device(self, device: DeviceLike) -> None:
        """Canonicalise and cache the device. ``_plain_device`` is the
        single-device fast-path key for :meth:`_input` (``None`` when the
        metric is mesh-placed): the hot-loop update path reads one attribute
        instead of re-deriving the sharding/device split per argument."""
        self._device = canonical_device(device)
        self._plain_device = (
            None
            if isinstance(self._device, jax.sharding.Sharding)
            else self._device
        )

    # ------------------------------------------------------------------ state
    def _add_state(
        self,
        name: str,
        default: TState,
        *,
        reduction: Optional[Reduction] = None,
    ) -> None:
        """Register a state variable and its cross-replica reduction.

        ``default`` may be an array(-like), a list, a dict, or a deque of
        arrays. If ``reduction`` is omitted it is inferred: lists/deques → CAT,
        everything else → SUM (the dominant merge in the reference, §2.2).
        """
        if not isinstance(default, (list, dict, deque, np.ndarray)):
            # scalars / nested python lists / torch tensors become jax
            # arrays as before; host numpy defaults (zeros_state on donating
            # backends) stay host-side — the stored default is a schema
            # template, and keeping it off-device makes the two copy_state
            # snapshots below free (the live state still gets placed by
            # put_state, one transfer per state instead of four dispatches)
            default = jnp.asarray(default)
        check_state_type(name, default)
        if reduction is None:
            reduction = Reduction.CAT if isinstance(default, (list, deque)) else Reduction.SUM
        self._state_name_to_default[name] = copy_state(default)
        self._state_name_to_reduction[name] = reduction
        setattr(self, name, put_state(copy_state(default), self._device))

    @property
    def state_names(self) -> tuple:
        return tuple(self._state_name_to_default)

    def _states(self) -> Dict[str, TState]:
        return {n: getattr(self, n) for n in self._state_name_to_default}

    def _set_states(self, values: Dict[str, TState]) -> None:
        for name, value in values.items():
            setattr(self, name, value)

    def _input(self, x) -> jax.Array:
        """Convert an update argument (jax / numpy / torch-via-dlpack / python)
        to a ``jax.Array`` on this metric's device. Torch tensors arrive as
        committed host arrays, so the explicit placement is what makes mixing
        them with HBM-resident state legal."""
        # hot-loop head: a jax.Array already resident on a single-device
        # metric's device passes straight through — a concrete-type pointer
        # compare plus one sharding attribute read, no ABC isinstance, no
        # device-set construction (update() host time is the eval loop's
        # per-batch floor since the whole-window step removed every
        # per-batch device dispatch). ``_device`` only exists on
        # SingleDeviceSharding, so sharded inputs fall through to the full
        # path below; so does everything on a moved-internals jax
        # (_ARRAY_IMPL is None).
        if type(x) is _ARRAY_IMPL and self._plain_device is not None:
            if (
                getattr(x.sharding, "_device", None) is self._plain_device
            ):
                return x
            try:
                if self._plain_device in x.devices():
                    return x
            except Exception:
                pass
        elif (
            self._plain_device is not None
            and isinstance(x, jax.Array)
            and not isinstance(x, jax.core.Tracer)
        ):
            try:
                if self._plain_device in x.devices():
                    return x
            except Exception:
                pass
        from torcheval_tpu.utils.convert import as_jax

        if isinstance(x, jax.core.Tracer):
            # already inside a trace (a user jitting their eval step around
            # the metric): placement happened before the jit boundary; pass
            # straight through
            return x
        arr = as_jax(x)
        if isinstance(arr, jax.Array):
            # already where it needs to be → skip device_put entirely (it
            # costs ~75 µs per call even when it is a placement no-op, which
            # dominates the hot-loop update's host overhead). This holds for
            # committed AND uncommitted arrays: an uncommitted array whose
            # buffer already lives on the target device is accepted as-is by
            # the jitted kernel with no transfer.
            if isinstance(self._device, jax.sharding.Sharding):
                # mesh-placed metric: keep the caller's batch sharding when it
                # spans the metric's mesh — re-placing a data-sharded batch
                # with the metric's (replicated) sharding would silently
                # all-gather it. Arrays committed elsewhere (CPU-committed
                # torch imports, single-device subsets) still need the
                # transfer.
                if arr.sharding.device_set == self._device.device_set:
                    return arr
            else:
                try:
                    if self._device in arr.devices():
                        return arr
                except Exception:
                    pass
        return jax.device_put(arr, self._device)

    # --------------------------------------------------------------- protocol
    @abstractmethod
    def update(self: TSelf, *args: Any, **kwargs: Any) -> TSelf:
        """Fold a batch into the metric state. Must be cheap to call in a hot
        loop: implementations dispatch one jitted kernel and return without
        synchronising."""

    @abstractmethod
    def compute(self) -> TComputeReturn:
        """Fold state into the final result. Idempotent on the logical state.

        Deferred metrics (``metrics/deferred.py``) first fold pending batches
        into their state — a physical-representation change that rebinds
        the state attributes (and, on donating backends, deletes the old
        buffers) while preserving the logical value. Repeated ``compute``
        calls return the same result either way."""

    @abstractmethod
    def merge_state(self: TSelf, metrics: Iterable[TSelf]) -> TSelf:
        """Merge other replicas' state into self (other metrics unchanged)."""

    def _prepare_for_merge_state(self) -> None:
        """Pre-sync state compaction hook (e.g. concat a sample-cache list into
        one array so the collective moves one buffer). Reference:
        ``metric.py:112-121``."""
        self._fold_now()

    def _fold_now(self) -> None:
        """Fold any deferred pending batches into the logical state. No-op
        here; overridden by :class:`~torcheval_tpu.metrics.deferred.
        DeferredFoldMixin`. Every read path that must observe the logical
        state (``state_dict``, ``to``, pickling, sync) calls this first."""

    # ------------------------------------------------------------- life cycle
    def reset(self: TSelf) -> TSelf:
        """Reset all state variables to their registered defaults (placed on
        the metric's current device)."""
        for name, default in self._state_name_to_default.items():
            value = put_state(copy_state(default), self._device)
            if isinstance(default, dict) and not isinstance(value, defaultdict):
                # plain-dict defaults gain the reference's missing-key-is-zero
                # semantics after reset (metric.py:139-147); registered
                # defaultdicts keep their own factory (copy_state preserves it)
                d = defaultdict(_zero_scalar)
                d.update(value)
                value = d
            setattr(self, name, value)
        return self

    def state_dict(self) -> Dict[str, TState]:
        """Snapshot state as a plain dict (arrays are immutable — no clone
        needed, unlike the reference's detach+clone dance).

        On non-donating backends the snapshot may *alias* the live state
        buffers (see docs/design.md "State lifecycle"); that is safe unless
        user code later donates those arrays via
        ``jax.jit(..., donate_argnums=...)`` — donation is the one thing
        that can invalidate an immutable-array alias. Deep-copy the
        snapshot first if you must donate metric state."""
        self._fold_now()
        out: Dict[str, TState] = {}
        for name in self._state_name_to_default:
            value = getattr(self, name)
            check_state_type(name, value)
            out[name] = copy_state(value)
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        # fold BEFORE overwriting (ISSUE 5 satellite): pending deferred
        # chunks belong to the stream that produced the CURRENT state. Fold
        # them into it now so (a) they can never fold into the restored
        # state on the next read — a mid-window restore must be exact — and
        # (b) a partial load (strict=False naming only some states) keeps
        # their contribution in the states it does NOT overwrite; the old
        # drop-pending behavior silently lost those updates.
        self._fold_now()
        state_dict = dict(state_dict)
        names = set(self._state_name_to_default)
        for name in names:
            if name in state_dict:
                value = state_dict[name]
                check_state_type(name, value)
                # place on this metric's device: loaded arrays may be committed
                # elsewhere (e.g. a checkpoint taken on another host/device)
                setattr(self, name, put_state(copy_state(value), self._device))
        if strict:
            unexpected = set(state_dict) - names
            missing = names - set(state_dict)
            if missing or unexpected:
                raise RuntimeError(
                    f"Error(s) in loading state_dict for {type(self).__name__}. "
                    f"Encountered missing keys: {missing} and unexpected keys: "
                    f"{unexpected}."
                )

    def to(self: TSelf, device: DeviceLike, *args: Any, **kwargs: Any) -> TSelf:
        """Move all state to ``device`` (a jax.Device, platform string, or a
        ``Sharding`` for mesh-distributed state)."""
        self._fold_now()  # pending batches live on the old device
        self._bind_device(device)
        for name in self._state_name_to_default:
            setattr(self, name, put_state(getattr(self, name), self._device))
        return self

    @property
    def device(self):
        return self._device

    # ------------------------------------------------------------------ misc
    def __deepcopy__(self: TSelf, memo: Dict[int, Any]) -> TSelf:
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == "_device" or k == "_plain_device":
                # devices are process singletons: share, don't copy
                new.__dict__[k] = v
            else:
                new.__dict__[k] = _deepcopy_value(v, memo)
        return new

    def __getstate__(self) -> Dict[str, Any]:
        # jax.Device handles are process-local and unpicklable; serialise a
        # (platform, id) spec instead. Shardings degrade to the default device
        # on restore (cross-process restore cannot assume the same mesh).
        state = dict(self.__dict__)
        dev = state.pop("_device", None)
        state.pop("_plain_device", None)  # device handle cache: re-derived
        if isinstance(dev, jax.Device):
            state["_device_spec"] = (dev.platform, dev.id)
        else:
            state["_device_spec"] = None
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        spec = state.pop("_device_spec", None)
        self.__dict__.update(state)
        device = None
        if spec is not None:
            platform, dev_id = spec
            try:
                devs = jax.devices(platform)
                # match by device id, not list position: local ids need not be
                # 0..n-1 in multi-process jobs
                device = next((d for d in devs if d.id == dev_id), devs[0])
            except RuntimeError:
                device = None
        self._bind_device(device)

    def __repr__(self) -> str:
        return f"{type(self).__name__}(device={self._device})"

"""MetricCollection: drive many metrics from one batch with minimal dispatch.

SURVEY §3.1 names the goal for the hot loop: "a single fused jit'd XLA
computation (donated state in HBM)". Three lanes exist, picked per member:

* **Deferred counter metrics** (``metrics/deferred.py``: accuracy family,
  F1/precision/recall, confusion matrices) already make ``update`` an O(1)
  host append with a bulk fused fold later — strictly better than
  one-dispatch-per-batch fusion, so the collection leaves them on that path
  (re-tracing them here would drag them back to per-batch kernels).
* **Fusable array-state metrics** (regression, NE, Sum/Mean/Max/Min): traced
  once into a single jitted step over the joint state pytree, with the state
  **donated** so accumulators live in HBM and update in place — one dispatch
  per batch for all of them together.
* **Host-state metrics** (sample caches, dict/deque fixtures, Throughput's
  host scalars): eager path; their updates are O(1) host appends and were
  never dispatch-bound.

Whatever the lane, the collection converts/places each batch argument ONCE
(via the first metric's ``_input``) and hands every member the same placed
arrays — k metrics never pay k host→device transfers, and deferring members'
pending lists share one buffer per batch.

Donation caveat: after an ``update()`` (fused lane) or a deferred fold,
previously captured references to a member's state arrays are invalid (their
buffers were donated). Read state through the metric/collection (``compute``,
``state_dict``) instead of holding raw array refs across updates.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Union

import jax

from torcheval_tpu.metrics.deferred import group_fold
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.obs.annotate import traced as _traced
from torcheval_tpu.obs.recompile import watched_jit as _watched_jit

_logger = logging.getLogger(__name__)


def _is_fusable(metric: Metric) -> bool:
    """Array-state metrics trace; container-state metrics stay eager.

    Deferred-fold metrics (``metrics/deferred.py``) are excluded: their
    ``update`` is already an O(1) host append folded in bulk later, which
    beats one-dispatch-per-batch fusion — re-tracing them here would only
    drag them back to the eager per-batch kernel."""
    if getattr(metric, "_defers", False):
        return False
    return all(
        isinstance(v, jax.Array)
        for v in (metric._states() or {"": None}).values()
    ) and bool(metric._states())


class MetricCollection:
    """Drive several metrics with the same update arguments, placing each
    batch once and routing every member to its fastest lane (see module doc).

    Example::

        col = MetricCollection({
            "acc": MulticlassAccuracy(num_classes=1000),   # deferred append
            "f1": MulticlassF1Score(num_classes=1000, average="macro"),
            "mse": MeanSquaredError(),    # fusable: one jitted dispatch
            "auroc": BinaryAUROC(),       # cache metric: eager append
        })
        for scores, labels in loader:
            col.update(scores, labels)
        results = col.compute()

    All member metrics receive identical ``update(*args, **kwargs)``; build
    separate collections for metrics fed from different tensors.
    """

    def __init__(self, metrics: Union[Metric, Dict[str, Metric]]) -> None:
        self._single = isinstance(metrics, Metric)
        self.metrics: Dict[str, Metric] = (
            {"metric": metrics} if self._single else dict(metrics)
        )
        if not self.metrics:
            raise ValueError("MetricCollection needs at least one metric.")
        self._fused = [n for n, m in self.metrics.items() if _is_fusable(m)]
        self._eager = [n for n in self.metrics if n not in self._fused]
        # deferred members fold TOGETHER (one dispatch, shared subcomputations
        # CSE'd by XLA) with the collection owning the fold trigger
        self._deferred = {
            n: m for n, m in self.metrics.items() if getattr(m, "_defers", False)
        }
        for m in self._deferred.values():
            m._defer_managed = True
        self._step = self._build_step() if self._fused else None

    def _build_step(self):
        fused, metrics = self._fused, self.metrics

        def step(states: Dict[str, Dict[str, jax.Array]], args, kwargs):
            out: Dict[str, Dict[str, jax.Array]] = {}
            for name in fused:
                m = metrics[name]
                saved = m._states()
                try:
                    m._set_states(states[name])
                    m.update(*args, **kwargs)
                    out[name] = m._states()
                finally:
                    m._set_states(saved)
            return out

        from torcheval_tpu.utils.platform import donation_pipelines

        # donation keeps the accumulators updating in place in HBM; on a
        # tunneled backend it serialises dispatches instead (7x slower
        # measured) — see utils/platform.py. watched_jit: the fused step is
        # the canonical place a drifting batch signature turns into a
        # retrace storm, and its HLO carries the collection's scope name.
        if donation_pipelines():
            return _watched_jit(step, name="collection.step", donate_argnums=0)
        return _watched_jit(step, name="collection.step")

    @_traced("collection.update")
    def update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        # convert + place each batch argument ONCE for the whole collection:
        # torch/numpy batches must land on the metrics' device before the jit
        # boundary anyway (the traced update's _input is a passthrough for
        # tracers), and eager/deferred members then hit _input's already-
        # placed fast path instead of re-transferring per metric
        place = next(iter(self.metrics.values()))._input
        args = tuple(
            place(a)
            if hasattr(a, "__array__") or hasattr(a, "__dlpack__")
            else a
            for a in args
        )
        kwargs = {
            k: place(v)
            if hasattr(v, "__array__") or hasattr(v, "__dlpack__")
            else v
            for k, v in kwargs.items()
        }
        if self._step is not None:
            states = {n: self.metrics[n]._states() for n in self._fused}
            new_states = self._step(states, args, kwargs)
            for name in self._fused:
                self.metrics[name]._set_states(new_states[name])
        for name in self._eager:
            self.metrics[name].update(*args, **kwargs)
        if self._deferred:
            # collection-owned budget trigger: every deferred member carries
            # the same pending arrays, so one member's budget speaks for all
            probe = next(iter(self._deferred.values()))
            if (
                probe._pending_bytes >= probe._DEFER_BUDGET_BYTES
                or len(probe._pending) >= probe._DEFER_MAX_CHUNKS
            ):
                group_fold(self._deferred)
        return self

    @_traced("collection.compute")
    def compute(self) -> Any:
        if self._deferred:
            group_fold(self._deferred)
        out = {n: m.compute() for n, m in self.metrics.items()}
        return out["metric"] if self._single else out

    def reset(self) -> "MetricCollection":
        for m in self.metrics.values():
            m.reset()
        return self

    def state_dicts(self) -> Dict[str, Dict[str, Any]]:
        if self._deferred:
            group_fold(self._deferred)
        return {n: m.state_dict() for n, m in self.metrics.items()}

    def __getitem__(self, name: str) -> Metric:
        return self.metrics[name]

    def __repr__(self) -> str:
        kinds = ", ".join(
            f"{n}{'*' if n in self._fused else ''}" for n in self.metrics
        )
        return f"MetricCollection({kinds})  (* = fused)"

"""MetricCollection: drive many metrics from one batch with minimal dispatch.

SURVEY §3.1 names the goal for the hot loop: "a single fused jit'd XLA
computation (donated state in HBM)". Since the lane unification (ISSUE 2)
the collection has ONE device pipeline and one host pipeline:

* **Deferred array-state metrics** (``metrics/deferred.py``: the counter
  families, regression/NE sufficient statistics, Sum/Mean/Max/Min, CTR,
  calibration) make ``update`` an O(1) host append. The collection owns the
  fold trigger: all deferred members' pending batches fold TOGETHER in one
  XLA program per budget window (``group_fold``), so XLA CSEs their shared
  math, and under a steady constant-batch loop the fold runs the scan-based
  stacked path with an O(1) trace and retrace-signature space. This replaced
  the old per-batch fused ``collection.step`` jit — one dispatch per batch
  was still O(batches) dispatches; one fold per budget window is
  O(total_bytes / budget).
* **Host-state metrics** (sample caches, dict/deque fixtures, Throughput's
  host scalars): eager path; their updates are O(1) host appends and were
  never dispatch-bound.

Whatever the lane, the collection converts/places each batch argument ONCE
(via the first metric's ``_input``, resolved at construction) and hands every
member the same placed arrays — k metrics never pay k host→device transfers,
and deferring members' pending lists share one buffer per batch. The
per-argument "is this an array-like that needs placement" dispatch is
memoised per *type* at first sight, so the steady-loop ``update()`` does no
``hasattr`` protocol probing.

A custom third-party metric with array state that does not opt into
``DeferredFoldMixin`` simply runs its own eager ``update`` per batch — the
pre-unification fused lane that re-traced such metrics into a per-batch
program is gone (it measured *slower* than deferral and forced a
``_states()``/``_set_states()`` save-restore round trip on every update).

Donation caveat (unchanged semantics, new trigger): after a deferred fold,
previously captured references to a member's state arrays are invalid on
donating backends (their buffers were donated). Read state through the
metric/collection (``compute``, ``state_dict``) instead of holding raw array
refs across updates.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Union

from torcheval_tpu.metrics.deferred import group_fold
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.obs.annotate import traced as _traced

_logger = logging.getLogger(__name__)

# type -> needs-placement decision, memoised at first sight: the array-like
# protocols (__array__ / __dlpack__) are class-level in every real producer
# (numpy, torch, jax), so two hasattr probes per ARG TYPE replace two per
# arg per update call.
_placeable_types: Dict[type, bool] = {}


def _needs_placement(t: type) -> bool:
    flag = _placeable_types.get(t)
    if flag is None:
        flag = _placeable_types[t] = bool(
            hasattr(t, "__array__") or hasattr(t, "__dlpack__")
        )
    return flag


class MetricCollection:
    """Drive several metrics with the same update arguments, placing each
    batch once and routing every member to its fastest lane (see module doc).

    Example::

        col = MetricCollection({
            "acc": MulticlassAccuracy(num_classes=1000),   # deferred append
            "f1": MulticlassF1Score(num_classes=1000, average="macro"),
            "mse": MeanSquaredError(),    # deferred append (same fold program)
            "auroc": BinaryAUROC(),       # cache metric: eager append
        })
        for scores, labels in loader:
            col.update(scores, labels)
        results = col.compute()

    All member metrics receive identical ``update(*args, **kwargs)``; build
    separate collections for metrics fed from different tensors.
    """

    def __init__(self, metrics: Union[Metric, Dict[str, Metric]]) -> None:
        self._single = isinstance(metrics, Metric)
        self.metrics: Dict[str, Metric] = (
            {"metric": metrics} if self._single else dict(metrics)
        )
        if not self.metrics:
            raise ValueError("MetricCollection needs at least one metric.")
        # deferred members fold TOGETHER (one dispatch, shared subcomputations
        # CSE'd by XLA) with the collection owning the fold trigger
        self._deferred = {
            n: m for n, m in self.metrics.items() if getattr(m, "_defers", False)
        }
        for m in self._deferred.values():
            m._defer_managed = True
        # hot-loop precomputation (host-overhead diet): the placement closure,
        # the members' bound update methods, and the budget probe are all
        # resolved once here instead of per update() call
        self._place = next(iter(self.metrics.values()))._input
        self._member_updates = tuple(m.update for m in self.metrics.values())
        self._defer_probe = (
            next(iter(self._deferred.values())) if self._deferred else None
        )

    @_traced("collection.update")
    def update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        # convert + place each batch argument ONCE for the whole collection:
        # torch/numpy batches must land on the metrics' device before any
        # fold anyway, and eager/deferred members then hit _input's already-
        # placed fast path instead of re-transferring per metric
        place = self._place
        args = tuple(
            place(a) if _needs_placement(type(a)) else a for a in args
        )
        if kwargs:
            kwargs = {
                k: place(v) if _needs_placement(type(v)) else v
                for k, v in kwargs.items()
            }
        for member_update in self._member_updates:
            member_update(*args, **kwargs)
        probe = self._defer_probe
        if probe is not None and (
            # collection-owned budget trigger: every deferred member carries
            # the same pending arrays, so one member's budget speaks for all
            probe._pending_bytes >= probe._DEFER_BUDGET_BYTES
            or len(probe._pending) >= probe._DEFER_MAX_CHUNKS
        ):
            group_fold(self._deferred)
        return self

    @_traced("collection.compute")
    def compute(self) -> Any:
        if self._deferred:
            group_fold(self._deferred)
        out = {n: m.compute() for n, m in self.metrics.items()}
        return out["metric"] if self._single else out

    def reset(self) -> "MetricCollection":
        for m in self.metrics.values():
            m.reset()
        return self

    def state_dicts(self) -> Dict[str, Dict[str, Any]]:
        if self._deferred:
            group_fold(self._deferred)
        return {n: m.state_dict() for n, m in self.metrics.items()}

    def load_state_dicts(
        self, state_dicts: Dict[str, Dict[str, Any]], strict: bool = True
    ) -> "MetricCollection":
        """Install per-member state dicts (the inverse of
        :meth:`state_dicts`; the checkpoint restore path,
        ``torcheval_tpu.resilience``). ``strict`` mirrors
        ``Metric.load_state_dict`` at the collection level: the metric-key
        sets must match exactly. Members fold any pending deferred chunks
        into their OLD state before installing (``Metric.load_state_dict``),
        so a mid-stream restore is exact."""
        if strict:
            unexpected = set(state_dicts) - set(self.metrics)
            missing = set(self.metrics) - set(state_dicts)
            if missing or unexpected:
                raise RuntimeError(
                    "Error(s) in loading state_dicts for MetricCollection. "
                    f"Encountered missing metric keys: {missing} and "
                    f"unexpected metric keys: {unexpected}."
                )
        for name, sd in state_dicts.items():
            if name in self.metrics:
                self.metrics[name].load_state_dict(sd, strict)
        return self

    def __getitem__(self, name: str) -> Metric:
        return self.metrics[name]

    def __repr__(self) -> str:
        kinds = ", ".join(
            f"{n}{'*' if n in self._deferred else ''}" for n in self.metrics
        )
        return f"MetricCollection({kinds})  (* = deferred)"

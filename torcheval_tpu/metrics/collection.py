"""MetricCollection: fold many metric updates into ONE jitted dispatch.

SURVEY §3.1 names the goal for the hot loop: "a single fused jit'd XLA
computation (donated state in HBM)". Class metrics are convenient but eager:
each ``update()`` costs several dispatches (input placement, kernel, state
rebinds), and at small batches that host/dispatch overhead — not device math —
dominates (measured ~3.8 ms/update for MulticlassAccuracy at batch 8192 on a
tunneled v5e, where the kernel itself is 70 µs).

``MetricCollection`` traces every member metric's *existing* ``update``
method once into a single jitted step over the joint state pytree, with the
state **donated** so accumulators live in HBM and update in place. One
dispatch per batch for the whole collection, async end to end.

Only array-state metrics fuse (counter metrics — the hot ones). Metrics with
host-side state (sample caches, dict/deque fixtures, Throughput's host
scalars) automatically stay on their eager path inside the same collection;
their updates are O(1) host appends, so they were never dispatch-bound.

Donation caveat: after an ``update()``, previously captured references to a
fused metric's state arrays are invalid (their buffers were donated). Read
state through the metric/collection (``compute``, ``state_dict``) instead of
holding raw array refs across updates.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Union

import jax

from torcheval_tpu.metrics.metric import Metric

_logger = logging.getLogger(__name__)


def _is_fusable(metric: Metric) -> bool:
    """Array-state metrics trace; container-state metrics stay eager."""
    return all(
        isinstance(v, jax.Array)
        for v in (metric._states() or {"": None}).values()
    ) and bool(metric._states())


class MetricCollection:
    """Drive several metrics with the same update arguments in one dispatch.

    Example::

        col = MetricCollection({
            "acc": MulticlassAccuracy(num_classes=1000),
            "f1": MulticlassF1Score(num_classes=1000, average="macro"),
            "auroc": BinaryAUROC(),       # cache metric: eager path, still fine
        })
        for scores, labels in loader:
            col.update(scores, labels)    # ONE jitted call for acc+f1
        results = col.compute()

    All member metrics receive identical ``update(*args, **kwargs)``; build
    separate collections for metrics fed from different tensors.
    """

    def __init__(self, metrics: Union[Metric, Dict[str, Metric]]) -> None:
        self._single = isinstance(metrics, Metric)
        self.metrics: Dict[str, Metric] = (
            {"metric": metrics} if self._single else dict(metrics)
        )
        if not self.metrics:
            raise ValueError("MetricCollection needs at least one metric.")
        self._fused = [n for n, m in self.metrics.items() if _is_fusable(m)]
        self._eager = [n for n in self.metrics if n not in self._fused]
        self._step = self._build_step() if self._fused else None

    def _build_step(self):
        fused, metrics = self._fused, self.metrics

        def step(states: Dict[str, Dict[str, jax.Array]], args, kwargs):
            out: Dict[str, Dict[str, jax.Array]] = {}
            for name in fused:
                m = metrics[name]
                saved = m._states()
                try:
                    m._set_states(states[name])
                    m.update(*args, **kwargs)
                    out[name] = m._states()
                finally:
                    m._set_states(saved)
            return out

        from torcheval_tpu.utils.platform import donation_pipelines

        # donation keeps the accumulators updating in place in HBM; on a
        # tunneled backend it serialises dispatches instead (7x slower
        # measured) — see utils/platform.py
        if donation_pipelines():
            return jax.jit(step, donate_argnums=0)
        return jax.jit(step)

    def update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        if self._step is not None:
            # torch/numpy batches must convert AND land on the metrics'
            # device BEFORE the jit boundary (the traced update's _input is a
            # passthrough for tracers); reuse the eager placement semantics
            # of the first fused metric
            place = self.metrics[self._fused[0]]._input
            args = tuple(
                place(a)
                if hasattr(a, "__array__") or hasattr(a, "__dlpack__")
                else a
                for a in args
            )
            kwargs = {
                k: place(v)
                if hasattr(v, "__array__") or hasattr(v, "__dlpack__")
                else v
                for k, v in kwargs.items()
            }
            states = {n: self.metrics[n]._states() for n in self._fused}
            new_states = self._step(states, args, kwargs)
            for name in self._fused:
                self.metrics[name]._set_states(new_states[name])
        for name in self._eager:
            self.metrics[name].update(*args, **kwargs)
        return self

    def compute(self) -> Any:
        out = {n: m.compute() for n, m in self.metrics.items()}
        return out["metric"] if self._single else out

    def reset(self) -> "MetricCollection":
        for m in self.metrics.values():
            m.reset()
        return self

    def state_dicts(self) -> Dict[str, Dict[str, Any]]:
        return {n: m.state_dict() for n, m in self.metrics.items()}

    def __getitem__(self, name: str) -> Metric:
        return self.metrics[name]

    def __repr__(self) -> str:
        kinds = ", ".join(
            f"{n}{'*' if n in self._fused else ''}" for n in self.metrics
        )
        return f"MetricCollection({kinds})  (* = fused)"

"""MetricCollection: drive many metrics from one batch with one program.

SURVEY §3.1 names the goal for the hot loop: "a single fused jit'd XLA
computation (donated state in HBM)". Since the whole-window compiled eval
step (ISSUE 6) the collection IS that computation, at window granularity:

* **Deferred array-state metrics** (``metrics/deferred.py``: the counter
  families, regression/NE sufficient statistics, Sum/Mean/Max/Min, CTR,
  calibration) never see per-batch python at all on the steady path.
  ``update()`` is a pure host-side accumulator: it places each batch ONCE
  and appends the placed refs to a collection-owned
  :class:`~torcheval_tpu.metrics.deferred.EvalWindow` — zero per-batch
  device dispatch AND zero per-member python. Validation runs through the
  real member ``update()`` methods exactly once per batch signature (the
  slow path below) and is memoised; every later same-signature batch takes
  the append-only fast path. When the window closes — on the memory
  budget, at ``compute()`` or ``state_dicts()`` — ONE donated pjit program
  (``deferred.window_step``) contains every member's per-batch update math
  over the stacked chunks, the fold into every state tree, and (at
  ``compute()`` time) each member's terminal ``_compute_fn``, so XLA CSEs
  the members' shared math and reuses the donated HBM in place.
* **Host-state metrics** (sample caches, dict/deque fixtures, Throughput's
  host scalars) and custom array-state metrics without
  ``DeferredFoldMixin``: eager path, their ``update`` runs per batch as
  before. A collection containing any such member never donates the shared
  chunk buffers (the eager members may hold references to them).

Whatever the lane, the collection converts/places each batch argument ONCE
(via the first metric's ``_input``, resolved at construction) and hands every
member the same placed arrays — k metrics never pay k host→device transfers.
The per-argument "is this an array-like that needs placement" dispatch is
memoised per *type* at first sight, so the steady-loop ``update()`` does no
``hasattr`` protocol probing.

Batches whose derived chunk differs from the update args (keyword arguments,
scalar weights that become extra chunk columns) keep the pre-window lane:
member updates run per batch and the members' own pending lists group-fold
in one program per window, exactly the ISSUE-2 behavior.

Program sharing across collections (ISSUE 8): the window/group programs
key on canonical POSITIONAL member keys (``metrics/deferred.py``), so two
collections holding the same metric classes/configs in the same order
share one compiled program whatever their members are named — the
property that lets ``torcheval_tpu.serve`` run hundreds of tenants (one
collection each) off a handful of compiled programs.

Per-cohort eval (ISSUE 15): :class:`~torcheval_tpu.metrics.sliced.
SlicedMetricCollection` subclasses this collection — its ``update`` interns
the batch's ``slice_ids`` column into dense rows host-side and then rides
``_update_impl`` verbatim, so the window fast path, signature memoisation,
budget valve and one-program close below serve the sliced members (whose
states carry a leading slice axis) without modification.

Donation caveat (unchanged semantics, window trigger): after a window step,
previously captured references to a member's state arrays are invalid on
donating backends (their buffers were donated). Read state through the
metric/collection (``compute``, ``state_dicts``) instead of holding raw
array refs across updates. Chunk buffers are donated only when every chunk
in the window was created by this collection's own placement (host batches:
numpy/python inputs), never when the caller handed in ``jax.Array``s or
torch tensors it may still hold.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Union

import jax

from torcheval_tpu.metrics.deferred import EvalWindow
from torcheval_tpu.metrics.metric import _ARRAY_IMPL, Metric
from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _obs_trace
from torcheval_tpu.obs.annotate import traced as _traced
from torcheval_tpu.utils.convert import _is_torch_tensor

_logger = logging.getLogger(__name__)

# type -> needs-placement decision, memoised at first sight: the array-like
# protocols (__array__ / __dlpack__) are class-level in every real producer
# (numpy, torch, jax), so two hasattr probes per ARG TYPE replace two per
# arg per update call.
_placeable_types: Dict[type, bool] = {}


def _needs_placement(t: type) -> bool:
    flag = _placeable_types.get(t)
    if flag is None:
        flag = _placeable_types[t] = bool(
            hasattr(t, "__array__") or hasattr(t, "__dlpack__")
        )
    return flag


class MetricCollection:
    """Drive several metrics with the same update arguments, placing each
    batch once and routing every member to its fastest lane (see module doc).

    Example::

        col = MetricCollection({
            "acc": MulticlassAccuracy(num_classes=1000),   # window append
            "f1": MulticlassF1Score(num_classes=1000, average="macro"),
            "mse": MeanSquaredError(),    # window append (same program)
            "auroc": BinaryAUROC(),       # cache metric: eager append
        })
        for scores, labels in loader:
            col.update(scores, labels)
        results = col.compute()

    All member metrics receive identical ``update(*args, **kwargs)``; build
    separate collections for metrics fed from different tensors.
    """

    def __init__(self, metrics: Union[Metric, Dict[str, Metric]]) -> None:
        self._single = isinstance(metrics, Metric)
        self.metrics: Dict[str, Metric] = (
            {"metric": metrics} if self._single else dict(metrics)
        )
        if not self.metrics:
            raise ValueError("MetricCollection needs at least one metric.")
        # deferred members share ONE window and fold/compute TOGETHER in a
        # single window-step program per budget window (shared
        # subcomputations CSE'd by XLA), with the collection owning the
        # fold trigger
        self._deferred = {
            n: m for n, m in self.metrics.items() if getattr(m, "_defers", False)
        }
        self._window = (
            EvalWindow(self._deferred, owner=self) if self._deferred else None
        )
        for m in self._deferred.values():
            m._defer_managed = True
            # a LIST of windows: a metric wrapped by several collections
            # belongs to each one's window, and every direct read must
            # drain them all (Metric._fold_now) — a single-slot back
            # reference would silently orphan the earlier windows' chunks
            windows = getattr(m, "_defer_windows", None)
            if windows is None:
                windows = m._defer_windows = []
            windows.append(self._window)
        # hot-loop precomputation (host-overhead diet): the placement closure,
        # the members' bound update methods, and the budget probe are all
        # resolved once here instead of per update() call
        self._place = next(iter(self.metrics.values()))._input
        self._deferred_updates = tuple(
            m.update for m in self._deferred.values()
        )
        self._eager_updates = tuple(
            m.update for n, m in self.metrics.items() if n not in self._deferred
        )
        self._defer_probe = (
            next(iter(self._deferred.values())) if self._deferred else None
        )
        # chunk buffers handed to eager members may be retained by them (a
        # sample cache aliasing the placed batch), so a mixed collection
        # never donates chunks — only all-deferred collections can prove
        # window ownership
        self._chunks_ownable = not self._eager_updates
        # the window fast path appends the batch WITHOUT calling member
        # update() methods again, so it is only safe when every deferred
        # member runs the library's own update (whose whole per-batch effect
        # is the _defer append the window replays). A subclass/third-party
        # override may carry side effects (logging, extra validation) that
        # must run per batch — those collections keep the per-member lane
        self._window_armable = all(
            getattr(type(m).update, "__module__", "").startswith(
                "torcheval_tpu."
            )
            for m in self._deferred.values()
        )
        # same contract for the terminal compute: the window close runs the
        # class-level _compute_fn INSTEAD of calling member compute(), so a
        # member whose compute() is overridden outside the library (post-
        # processing, unit changes) must fall back to its own compute() —
        # the window still folds its state, only the terminal stays member-own
        self._window_compute_keys = tuple(
            n
            for n, m in self._deferred.items()
            if getattr(type(m).compute, "__module__", "").startswith(
                "torcheval_tpu."
            )
        )

    @_traced("collection.update")
    def update(self, *args: Any, **kwargs: Any) -> "MetricCollection":
        return self._update_impl(args, kwargs, False)

    @_traced("collection.update")
    def update_placed(
        self, args: tuple, *, owned: bool = False
    ) -> "MetricCollection":
        """``update`` for batches ALREADY placed on device by a trusted
        ingest pipeline (the serve daemon's coalesced H2D stage, ISSUE
        11). ``owned=True`` is the caller's vouch that every device
        buffer in ``args`` was created by its own transfer and is
        referenced by no one else — which re-arms chunk donation that a
        plain ``update`` must refuse for caller-passed device arrays (it
        cannot know who else holds them). Never pass ``owned=True`` for a
        buffer any other window/caller can still read: a donated chunk's
        next read is a deleted-array error."""
        return self._update_impl(args, None, owned)

    def _update_impl(
        self, args: tuple, kwargs: Any, placed_owned: bool
    ) -> "MetricCollection":
        # convert + place each batch argument ONCE for the whole collection:
        # torch/numpy batches must land on the metrics' device before any
        # fold anyway, and eager/deferred members then hit _input's already-
        # placed fast path instead of re-transferring per metric
        place = self._place
        window = self._window
        owned = self._chunks_ownable
        # window-appendable: at least one positional arg, all placed, no
        # kwargs — everything else routes through the member updates
        direct = bool(args) and not kwargs
        placed = []
        for a in args:
            if _needs_placement(type(a)):
                p = place(a)
                if (p is a and not placed_owned) or _is_torch_tensor(a):
                    # the caller may still hold this buffer (jax passthrough)
                    # or alias it (torch via zero-copy dlpack): never donate
                    owned = False
                placed.append(p)
            else:
                placed.append(a)
                direct = False  # python scalars etc.: member updates convert
        args = tuple(placed)
        kwargs = kwargs or {}
        if kwargs:
            kwargs = {
                k: place(v) if _needs_placement(type(v)) else v
                for k, v in kwargs.items()
            }
        for member_update in self._eager_updates:
            member_update(*args, **kwargs)
        if window is None:
            return self
        if direct and self._window_armable:
            # signature compare without building a tuple per call: a flat
            # loop against the cached (shape, dtype) pairs. The concrete
            # ArrayImpl type compare stands in for the tracer check
            # (tracers are not ArrayImpl) at pointer-compare cost.
            sig = window.sig
            match = sig is not None and len(sig) == len(args)
            if match:
                for a, sd in zip(args, sig):
                    if (
                        type(a) is not _ARRAY_IMPL
                        or a.shape != sd[0]
                        or a.dtype != sd[1]
                    ):
                        match = False
                        break
            if match:
                # steady fast path: this exact batch signature has been
                # validated through the member updates before — append the
                # placed refs ONCE for the whole collection (byte size is a
                # pure signature function, cached beside it: Array.nbytes
                # costs ~4 µs per arg, half this path's budget)
                window.append(args, window.sig_nbytes, owned)
                self._window_budget_check()
                return self
            if not any(isinstance(a, jax.core.Tracer) for a in args):
                self._ingest_new_signature(
                    args,
                    kwargs,
                    tuple((a.shape, a.dtype) for a in args),
                    owned,
                )
            else:
                self._ingest_slow(args, kwargs)
        else:
            self._ingest_slow(args, kwargs)
        self._window_budget_check()
        return self

    def _ingest_new_signature(self, args, kwargs, sig, owned) -> None:
        """First batch of a (full-shape) signature: run the real member
        updates (their validation + per-member chunk derivation), then — if
        every deferred member appended exactly the update args as its chunk —
        migrate that one chunk into the shared window and arm the fast path
        for the signature."""
        window = self._window
        if window.chunks:
            head = window.chunks[0]
            if len(head) != len(args) or any(
                h.ndim != a.ndim
                or h.dtype != a.dtype
                or h.shape[1:] != a.shape[1:]
                for h, a in zip(head, args)
            ):
                # defer-signature change: one fold never mixes signatures —
                # close the open window before the members see the new batch
                window.fold()
        members = self._deferred.values()
        depths = [len(m._pending) for m in members]
        for member_update in self._deferred_updates:
            member_update(*args, **kwargs)
        # migration: every member's newly appended chunk must BE the update
        # args (identity) — true for every shipped deferred metric fed
        # positional batches; derived chunks (extra weight columns) keep the
        # per-member pending lane
        aligned = True
        for m, depth in zip(members, depths):
            p = m._pending
            if (
                len(p) != depth + 1
                or len(p[-1]) != len(args)
                or any(x is not y for x, y in zip(p[-1], args))
            ):
                aligned = False
                break
        if not aligned:
            window.sig = None  # keep routing through member updates
            return
        nbytes = sum(int(a.nbytes) for a in args)
        for m in members:
            m._pending.pop()
            m._pending_bytes = max(m._pending_bytes - nbytes, 0)
        window.append(args, nbytes, owned)
        window.sig = sig
        window.sig_nbytes = nbytes

    def _ingest_slow(self, args, kwargs) -> None:
        """kwargs / scalar / tracer batches: the pre-window lane — member
        updates run per batch and the members' own pending lists group-fold
        per budget window."""
        for member_update in self._deferred_updates:
            member_update(*args, **kwargs)

    def _window_budget_check(self) -> None:
        # collection-owned budget trigger: window chunks plus any stray
        # member pending (direct streaming / the kwargs lane) count against
        # ONE budget, read from the probe member so per-instance overrides
        # (tests, tuning) keep working
        probe = self._defer_probe
        window = self._window
        if (
            window.nbytes + probe._pending_bytes >= probe._DEFER_BUDGET_BYTES
            or len(window.chunks) + len(probe._pending)
            >= probe._DEFER_MAX_CHUNKS
        ):
            if _obs._enabled:
                # the mid-stream budget valve firing is a timeline moment:
                # it explains every fold that happens before a compute()
                _obs_trace.instant(
                    "deferred.window.valve",
                    kind="window",
                    chunks=len(window.chunks),
                    bytes=window.nbytes,
                )
            window.fold()

    @_traced("collection.compute")
    def compute(self) -> Any:
        out: Dict[str, Any] = {}
        if self._window is not None:
            # close the window WITH the terminal computes: members with a
            # pure _compute_fn get their result from inside the same
            # program that folds the last chunks (zero extra dispatches)
            results = self._window.close(
                compute_keys=self._window_compute_keys
            )
            for n, result in results.items():
                out[n] = self.metrics[n]._on_window_result(result)
        ordered = {
            n: out[n] if n in out else m.compute()
            for n, m in self.metrics.items()
        }
        return ordered["metric"] if self._single else ordered

    def reset(self) -> "MetricCollection":
        if self._window is not None:
            # a collection-level reset discards the whole open window (the
            # same drop-pending semantics as Metric.reset) BEFORE member
            # resets, so no member pays a fold for chunks being thrown away
            self._window.clear()
        for m in self.metrics.values():
            m.reset()
        return self

    def state_dicts(self) -> Dict[str, Dict[str, Any]]:
        if self._window is not None:
            self._window.close()  # fold-only: snapshots want exact state
        return {n: m.state_dict() for n, m in self.metrics.items()}

    def load_state_dicts(
        self, state_dicts: Dict[str, Dict[str, Any]], strict: bool = True
    ) -> "MetricCollection":
        """Install per-member state dicts (the inverse of
        :meth:`state_dicts`; the checkpoint restore path,
        ``torcheval_tpu.resilience``). ``strict`` mirrors
        ``Metric.load_state_dict`` at the collection level: the metric-key
        sets must match exactly. Members fold any pending deferred chunks
        into their OLD state before installing (``Metric.load_state_dict``),
        so a mid-stream restore is exact."""
        if strict:
            unexpected = set(state_dicts) - set(self.metrics)
            missing = set(self.metrics) - set(state_dicts)
            if missing or unexpected:
                raise RuntimeError(
                    "Error(s) in loading state_dicts for MetricCollection. "
                    f"Encountered missing metric keys: {missing} and "
                    f"unexpected metric keys: {unexpected}."
                )
        for name, sd in state_dicts.items():
            if name in self.metrics:
                self.metrics[name].load_state_dict(sd, strict)
        return self

    def __getitem__(self, name: str) -> Metric:
        return self.metrics[name]

    def __repr__(self) -> str:
        kinds = ", ".join(
            f"{n}{'*' if n in self._deferred else ''}" for n in self.metrics
        )
        return f"MetricCollection({kinds})  (* = deferred)"

"""BinaryNormalizedEntropy metric. Reference:
``torcheval/metrics/classification/binary_normalized_entropy.py:22-147``."""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_num_tasks,
)
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _baseline_entropy,
    _binary_normalized_entropy_update,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike

_STATE_NAMES = ("total_entropy", "num_examples", "num_positive")


class BinaryNormalizedEntropy(Metric[jax.Array]):
    """Streaming normalized binary cross entropy (CTR calibration metric).

    Args:
        from_logits: interpret update inputs as logits rather than
            probabilities.
        num_tasks: number of parallel tasks; state has shape ``(num_tasks,)``.

    Reference parity: ``classification/binary_normalized_entropy.py:22-147``
    (float32 accumulators instead of float64 — TPU has no fast fp64; see the
    functional module's note).
    """

    def __init__(
        self,
        *,
        from_logits: bool = False,
        num_tasks: int = 1,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        self.from_logits = from_logits
        check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        for name in _STATE_NAMES:
            self._add_state(
                name,
                zeros_state((num_tasks,), dtype=jnp.float32),
                reduction=Reduction.SUM,
            )

    def update(
        self, input, target, *, weight: Optional[jax.Array] = None
    ) -> "BinaryNormalizedEntropy":
        raw_input = input
        input, target = self._input(input), self._input(target)
        if weight is not None:
            weight = self._input(weight)
        cross_entropy, num_positive, num_examples = (
            _binary_normalized_entropy_update(
                input, target, self.from_logits, self.num_tasks, weight,
                value_check_source=raw_input,
            )
        )
        self.total_entropy = self.total_entropy + cross_entropy
        self.num_examples = self.num_examples + num_examples
        self.num_positive = self.num_positive + num_positive
        return self

    def compute(self) -> jax.Array:
        if np.any(np.asarray(self.num_examples) == 0.0):
            return jnp.empty((0,))
        baseline = _baseline_entropy(self.num_positive, self.num_examples)
        return (self.total_entropy / self.num_examples) / baseline

    def merge_state(
        self, metrics: Iterable["BinaryNormalizedEntropy"]
    ) -> "BinaryNormalizedEntropy":
        for metric in metrics:
            for name in _STATE_NAMES:
                setattr(
                    self,
                    name,
                    getattr(self, name)
                    + jax.device_put(getattr(metric, name), self.device),
                )
        return self

"""BinaryNormalizedEntropy metric. Reference:
``torcheval/metrics/classification/binary_normalized_entropy.py:22-147``.

Updates are **deferred** (``metrics/deferred.py``): ``update()`` runs the
host-side shape/value checks (the [0, 1] probability check reads the RAW
pre-placement input, so it still happens per update, never inside a fold)
and appends the placed batch; the entropy fold runs over the pending stream
in one fused dispatch at read time or on a memory budget.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_num_tasks,
)
from torcheval_tpu.metrics.functional.classification.binary_normalized_entropy import (
    _baseline_entropy,
    _ne_fold,
    _ne_input_check,
    _ne_value_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike

_STATE_NAMES = ("total_entropy", "num_examples", "num_positive")


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py). The optional weight
# defers as an extra chunk column, so the trailing statics are parsed by
# arity: rest == (from_logits,) or (weight, from_logits).
def _ne_deferred_fold(input, target, *rest):
    if len(rest) == 2:
        weight, from_logits = rest
    else:
        weight, from_logits = None, rest[0]
    cross_entropy, num_positive, num_examples = _ne_fold(
        input, target, from_logits, weight
    )
    return {
        "total_entropy": cross_entropy,
        "num_examples": num_examples,
        "num_positive": num_positive,
    }


class BinaryNormalizedEntropy(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming normalized binary cross entropy (CTR calibration metric).

    Args:
        from_logits: interpret update inputs as logits rather than
            probabilities.
        num_tasks: number of parallel tasks; state has shape ``(num_tasks,)``.

    Reference parity: ``classification/binary_normalized_entropy.py:22-147``
    (float32 accumulators instead of float64 — TPU has no fast fp64; see the
    functional module's note).
    """

    _fold_fn = staticmethod(_ne_deferred_fold)
    _fold_per_chunk = True

    def __init__(
        self,
        *,
        from_logits: bool = False,
        num_tasks: int = 1,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        self.from_logits = from_logits
        check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        for name in _STATE_NAMES:
            self._add_state(
                name,
                zeros_state((num_tasks,), dtype=jnp.float32),
                reduction=Reduction.SUM,
            )
        self._init_deferred()
        self._fold_params = (from_logits,)

    def update(
        self, input, target, *, weight: Optional[jax.Array] = None
    ) -> "BinaryNormalizedEntropy":
        raw_input = input
        input, target = self._input(input), self._input(target)
        if weight is not None:
            weight = self._input(weight)
        _ne_input_check(input, target, self.from_logits, self.num_tasks, weight)
        # the [0, 1] check reads the RAW host-resident source (placed device
        # arrays skip it — documented divergence in the functional module)
        _ne_value_check(raw_input, self.from_logits)
        if weight is None:
            self._defer(input, target)
        else:
            self._defer(input, target, weight)
        return self

    def compute(self) -> jax.Array:
        self._fold_now()
        if np.any(np.asarray(self.num_examples) == 0.0):
            return jnp.empty((0,))
        baseline = _baseline_entropy(self.num_positive, self.num_examples)
        return (self.total_entropy / self.num_examples) / baseline

    def merge_state(
        self, metrics: Iterable["BinaryNormalizedEntropy"]
    ) -> "BinaryNormalizedEntropy":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            for name in _STATE_NAMES:
                setattr(
                    self,
                    name,
                    getattr(self, name)
                    + jax.device_put(getattr(metric, name), self.device),
                )
        return self

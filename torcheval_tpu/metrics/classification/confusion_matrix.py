"""Confusion-matrix class metrics (framework extension; see the functional
module for provenance — required by BASELINE config 3)."""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _confusion_matrix_input_check,
    _confusion_matrix_param_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.ops.confusion import confusion_matrix_counts, normalize_confusion_matrix
from torcheval_tpu.utils.devices import DeviceLike


class MulticlassConfusionMatrix(Metric[jax.Array]):
    """Streaming (num_classes, num_classes) confusion counts; rows = true."""

    def __init__(
        self,
        num_classes: int,
        *,
        normalize: Optional[str] = None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _confusion_matrix_param_check(num_classes, normalize)
        self.num_classes = num_classes
        self.normalize = normalize
        self._add_state(
            "confusion_matrix",
            jnp.zeros((num_classes, num_classes), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )

    def update(self, input, target) -> "MulticlassConfusionMatrix":
        input, target = self._input(input), self._input(target)
        _confusion_matrix_input_check(input, target, self.num_classes)
        if input.ndim == 2:
            input = jnp.argmax(input, axis=1)
        self.confusion_matrix = self.confusion_matrix + confusion_matrix_counts(
            input, target, self.num_classes
        )
        return self

    def compute(self) -> jax.Array:
        return normalize_confusion_matrix(self.confusion_matrix, self.normalize)

    def merge_state(
        self, metrics: Iterable["MulticlassConfusionMatrix"]
    ) -> "MulticlassConfusionMatrix":
        for metric in metrics:
            self.confusion_matrix = self.confusion_matrix + jax.device_put(
                metric.confusion_matrix, self.device
            )
        return self


class BinaryConfusionMatrix(MulticlassConfusionMatrix):
    """Streaming 2x2 confusion counts after thresholding scores."""

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        normalize: Optional[str] = None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(2, normalize=normalize, device=device)
        self.threshold = threshold

    def update(self, input, target) -> "BinaryConfusionMatrix":
        input, target = self._input(input), self._input(target)
        _confusion_matrix_input_check(input, target)
        pred = jnp.where(input < self.threshold, 0, 1)
        self.confusion_matrix = self.confusion_matrix + confusion_matrix_counts(
            pred, target, 2
        )
        return self

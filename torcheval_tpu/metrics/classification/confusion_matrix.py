"""Confusion-matrix class metrics (framework extension; see the functional
module for provenance — required by BASELINE config 3).

Updates are deferred (``metrics/deferred.py``): the joint-index count kernel
runs once over the concatenated pending batches, which lands it in the
large-N regime where the flat scatter lowering wins on TPU
(``ops/confusion.py`` crossover table) instead of 10-100 small per-batch
one-hot contractions.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.classification.confusion_matrix import (
    _confusion_matrix_input_check,
    _confusion_matrix_param_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.ops.confusion import confusion_matrix_counts, normalize_confusion_matrix
from torcheval_tpu.utils.devices import DeviceLike


def _cm_fold(input, target, num_classes):
    if input.ndim == 2:
        input = jnp.argmax(input, axis=1)
    return {
        "confusion_matrix": confusion_matrix_counts(input, target, num_classes)
    }


def _bincm_fold(input, target, threshold):
    pred = jnp.where(input < threshold, 0, 1)
    return {"confusion_matrix": confusion_matrix_counts(pred, target, 2)}


class MulticlassConfusionMatrix(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming (num_classes, num_classes) confusion counts; rows = true."""

    _fold_fn = staticmethod(_cm_fold)
    # pure terminal compute (count passthrough / normalization) riding the
    # window-step program at compute() time
    _compute_fn = staticmethod(normalize_confusion_matrix)

    def __init__(
        self,
        num_classes: int,
        *,
        normalize: Optional[str] = None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _confusion_matrix_param_check(num_classes, normalize)
        self.num_classes = num_classes
        self.normalize = normalize
        self._add_state(
            "confusion_matrix",
            zeros_state((num_classes, num_classes), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )
        self._init_deferred()
        self._fold_params = (num_classes,)
        self._compute_params = (normalize,)

    def _update_check(self, input, target) -> None:
        _confusion_matrix_input_check(input, target, self.num_classes)

    def update(self, input, target) -> "MulticlassConfusionMatrix":
        self._defer(self._input(input), self._input(target))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(
        self, metrics: Iterable["MulticlassConfusionMatrix"]
    ) -> "MulticlassConfusionMatrix":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.confusion_matrix = self.confusion_matrix + jax.device_put(
                metric.confusion_matrix, self.device
            )
        return self


class BinaryConfusionMatrix(MulticlassConfusionMatrix):
    """Streaming 2x2 confusion counts after thresholding scores."""

    _fold_fn = staticmethod(_bincm_fold)


    def __init__(
        self,
        *,
        threshold: float = 0.5,
        normalize: Optional[str] = None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(2, normalize=normalize, device=device)
        self.threshold = threshold
        self._fold_params = (threshold,)

    def _update_check(self, input, target) -> None:
        _confusion_matrix_input_check(input, target)

    def update(self, input, target) -> "BinaryConfusionMatrix":
        self._defer(self._input(input), self._input(target))
        return self

"""Binned PRC class metrics. Reference:
``torcheval/metrics/classification/binned_precision_recall_curve.py:27-247``.

The bounded-state streaming PR curve: counters of static shape
``(n_thresholds,)`` / ``(n_thresholds, num_classes)``, SUM-merged. This is
the recommended PRC form for the TPU hot path and for distributed sync.

Updates defer (``metrics/deferred.py``): the O(N·T) broadcast-compare kernel
runs once over the concatenated pending batches instead of per update. The
threshold grid is construction-time configuration, so it rides the fold's
static params as a tuple and is rebuilt as an XLA constant inside the
kernel.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.classification.binned_precision_recall_curve import (
    ThresholdSpec,
    _binary_binned_compute,
    _binary_binned_update,
    _binned_precision_recall_curve_param_check,
    _create_threshold_tensor,
    _multiclass_binned_compute,
    _multiclass_binned_update,
)
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check,
    _multiclass_precision_recall_curve_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike

_COUNTER_NAMES = ("num_tp", "num_fp", "num_fn")


def _threshold_fold_params(threshold) -> tuple:
    """Hashable static encoding of the threshold grid for the fold's jit
    cache key (rebuilt as an XLA constant inside the fold)."""
    return tuple(float(t) for t in np.asarray(threshold))


def _binary_binned_fold(input, target, thresholds):
    tp, fp, fn = _binary_binned_update(
        input, target, jnp.asarray(thresholds, jnp.float32)
    )
    return {"num_tp": tp, "num_fp": fp, "num_fn": fn}


def _binary_binned_deferred_compute(threshold, num_tp, num_fp, num_fn):
    """State-ordered terminal compute for the window-step program
    (``threshold`` registers first; it passes through as the third output)."""
    precision, recall = _binary_binned_compute(num_tp, num_fp, num_fn)
    return precision, recall, threshold


def _multiclass_binned_fold(input, target, thresholds, num_classes):
    tp, fp, fn = _multiclass_binned_update(
        input, target, jnp.asarray(thresholds, jnp.float32), num_classes
    )
    return {"num_tp": tp, "num_fp": fp, "num_fn": fn}


class BinaryBinnedPrecisionRecallCurve(
    DeferredFoldMixin, Metric[Tuple[jax.Array, jax.Array, jax.Array]]
):
    """Streaming binary PR curve over fixed thresholds.

    Args:
        threshold: bin count (int → ``linspace(0, 1)``), list, or array of
            sorted thresholds in ``[0, 1]``.
    """

    _fold_per_chunk = True

    _fold_fn = staticmethod(_binary_binned_fold)
    _compute_fn = staticmethod(_binary_binned_deferred_compute)

    def __init__(
        self, *, threshold: ThresholdSpec = 100, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        threshold = _create_threshold_tensor(threshold)
        _binned_precision_recall_curve_param_check(threshold)
        # threshold is configuration, not mergeable state — but the reference
        # registers it as state (:77), so we mirror that with MAX reduction
        # (identical across replicas; max is a no-op combiner)
        self._add_state("threshold", threshold, reduction=Reduction.MAX)
        n = threshold.shape[0]
        for name in _COUNTER_NAMES:
            self._add_state(
                name, zeros_state((n,), dtype=jnp.int32), reduction=Reduction.SUM
            )
        self._init_deferred()
        self._fold_params = (_threshold_fold_params(threshold),)

    def _update_check(self, input, target) -> None:
        _binary_precision_recall_curve_update_input_check(input, target)

    def update(self, input, target) -> "BinaryBinnedPrecisionRecallCurve":
        self._defer(self._input(input), self._input(target))
        return self

    def compute(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        return self._deferred_compute()

    def merge_state(
        self, metrics: Iterable["BinaryBinnedPrecisionRecallCurve"]
    ) -> "BinaryBinnedPrecisionRecallCurve":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            for name in _COUNTER_NAMES:
                setattr(
                    self,
                    name,
                    getattr(self, name)
                    + jax.device_put(getattr(metric, name), self.device),
                )
        return self


class MulticlassBinnedPrecisionRecallCurve(
    DeferredFoldMixin, Metric[Tuple[List[jax.Array], List[jax.Array], jax.Array]]
):
    """Streaming one-vs-all PR curves over fixed thresholds.

    Args:
        num_classes: number of classes (static; sizes the counter state).
        threshold: bin count, list, or sorted array in ``[0, 1]``.
    """

    _fold_per_chunk = True


    _fold_fn = staticmethod(_multiclass_binned_fold)

    def __init__(
        self,
        num_classes: int,
        *,
        threshold: ThresholdSpec = 100,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        if num_classes is None or num_classes < 2:
            raise ValueError(f"num_classes must be at least 2, got {num_classes}.")
        threshold = _create_threshold_tensor(threshold)
        _binned_precision_recall_curve_param_check(threshold)
        self.num_classes = num_classes
        self._add_state("threshold", threshold, reduction=Reduction.MAX)
        n = threshold.shape[0]
        for name in _COUNTER_NAMES:
            self._add_state(
                name,
                zeros_state((n, num_classes), dtype=jnp.int32),
                reduction=Reduction.SUM,
            )
        self._init_deferred()
        self._fold_params = (_threshold_fold_params(threshold), num_classes)

    def update(self, input, target) -> "MulticlassBinnedPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        _multiclass_precision_recall_curve_update_input_check(
            input, target, self.num_classes
        )
        self._defer(input, target)
        return self

    def compute(self) -> Tuple[List[jax.Array], List[jax.Array], jax.Array]:
        self._fold_now()
        precision, recall = _multiclass_binned_compute(
            self.num_tp, self.num_fp, self.num_fn
        )
        return list(precision.T), list(recall.T), self.threshold

    def merge_state(
        self, metrics: Iterable["MulticlassBinnedPrecisionRecallCurve"]
    ) -> "MulticlassBinnedPrecisionRecallCurve":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            for name in _COUNTER_NAMES:
                setattr(
                    self,
                    name,
                    getattr(self, name)
                    + jax.device_put(getattr(metric, name), self.device),
                )
        return self

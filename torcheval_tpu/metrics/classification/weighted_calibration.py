"""WeightedCalibration and its windowed variant.

Extensions beyond the reference snapshot (see the functional module's note).
Same state layout as :mod:`.click_through_rate`: two SUM scalars per task —
and the same lane split: the plain class is **deferred**
(``metrics/deferred.py``), the windowed variant stays eager because its
bounded per-update window (shared :mod:`._windowed` mixin) must see every
batch as its own row.
"""

from __future__ import annotations

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.classification._windowed import WindowedStateMixin
from torcheval_tpu.metrics.classification.click_through_rate import (
    _check_num_tasks,
)
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.classification.weighted_calibration import (
    _calibration_compute,
    _calibration_fold,
    _calibration_input_check,
    _weighted_calibration_update,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.devices import DeviceLike


def _fold_calibration(metric, input, target, weight):
    """Place inputs, run the fold, normalize to the ``(num_tasks,)`` axis —
    the eager helper the windowed class still uses per update (see
    ``_fold_ctr``)."""
    input, target = metric._input(input), metric._input(target)
    if weight is not None and hasattr(weight, "shape"):
        weight = metric._input(weight)
    pred, label = _weighted_calibration_update(
        input, target, metric.num_tasks, weight
    )
    return (
        jnp.reshape(pred, (metric.num_tasks,)),
        jnp.reshape(label, (metric.num_tasks,)),
    )


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py). Weighted updates
# defer the weight as a third chunk column, so the trailing statics are
# parsed by arity: rest == (num_tasks,) or (weight, num_tasks).
def _calibration_deferred_fold(input, target, *rest):
    num_tasks = rest[-1]
    weight = rest[0] if len(rest) == 2 else 1.0
    pred, label = _calibration_fold(input, target, as_jax(weight))
    return {
        "weighted_input_sum": jnp.reshape(pred, (num_tasks,)),
        "weighted_label_sum": jnp.reshape(label, (num_tasks,)),
    }


class WeightedCalibration(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming ``sum(w * input) / sum(w * target)`` per task."""

    _fold_fn = staticmethod(_calibration_deferred_fold)
    _fold_per_chunk = True
    # pure terminal compute riding the window-step program; update
    # validation stays eager (it branches on the weight argument)
    _compute_fn = staticmethod(_calibration_compute)

    def __init__(
        self, *, num_tasks: int = 1, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        _check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        for name in ("weighted_input_sum", "weighted_label_sum"):
            self._add_state(
                name,
                zeros_state((num_tasks,), dtype=jnp.float32),
                reduction=Reduction.SUM,
            )
        self._init_deferred()
        self._fold_params = (num_tasks,)

    def update(
        self,
        input,
        target,
        weight: Union[float, int, jax.Array, None] = None,
    ) -> "WeightedCalibration":
        input, target = self._input(input), self._input(target)
        if weight is None:
            _calibration_input_check(input, target, self.num_tasks, None)
            self._defer(input, target)
            return self
        if isinstance(weight, (int, float)):
            weight = as_jax(weight)
        else:
            weight = self._input(weight)
        _calibration_input_check(
            input, target, self.num_tasks, weight if weight.ndim else None
        )
        self._defer(input, target, weight)
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(
        self, metrics: Iterable["WeightedCalibration"]
    ) -> "WeightedCalibration":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.weighted_input_sum = self.weighted_input_sum + jax.device_put(
                metric.weighted_input_sum, self.device
            )
            self.weighted_label_sum = self.weighted_label_sum + jax.device_put(
                metric.weighted_label_sum, self.device
            )
        return self


class WindowedWeightedCalibration(
    WindowedStateMixin, Metric[Tuple[jax.Array, jax.Array]]
):
    """Calibration over the last ``window_size`` updates.

    Window/merge/compute semantics mirror
    :class:`~torcheval_tpu.metrics.WindowedClickThroughRate` (shared mixin):
    ``compute()`` returns ``(lifetime, windowed)`` when ``enable_lifetime``
    (default), else the windowed value alone; shapes ``(num_tasks,)``.
    Replicas must share the same window configuration to merge.
    """

    _LIFETIME_STATES = ("weighted_input_sum", "weighted_label_sum")

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        window_size: int = 100,
        enable_lifetime: bool = True,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        self.enable_lifetime = enable_lifetime
        if enable_lifetime:
            for name in self._LIFETIME_STATES:
                self._add_state(
                    name,
                    zeros_state((num_tasks,), dtype=jnp.float32),
                    reduction=Reduction.SUM,
                )
        self._init_window(window_size)

    def update(
        self,
        input,
        target,
        weight: Union[float, int, jax.Array, None] = None,
    ) -> "WindowedWeightedCalibration":
        pred, label = _fold_calibration(self, input, target, weight)
        if self.enable_lifetime:
            self.weighted_input_sum = self.weighted_input_sum + pred
            self.weighted_label_sum = self.weighted_label_sum + label
        self._push_window(pred, label)
        return self

    def compute(self):
        pred, label = self._window_totals()
        windowed = _calibration_compute(pred, label)
        if not self.enable_lifetime:
            return windowed
        return (
            _calibration_compute(
                self.weighted_input_sum, self.weighted_label_sum
            ),
            windowed,
        )

    def merge_state(
        self, metrics: Iterable["WindowedWeightedCalibration"]
    ) -> "WindowedWeightedCalibration":
        self._merge_windowed(metrics)
        return self

"""PRC class metrics. Reference:
``torcheval/metrics/classification/precision_recall_curve.py:29-220``."""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check,
    _multiclass_precision_recall_curve_update_input_check,
    binary_precision_recall_curve,
    multiclass_precision_recall_curve,
)
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.utils.devices import DeviceLike

_CurveResult = Tuple[jax.Array, jax.Array, jax.Array]


class BinaryPrecisionRecallCurve(SampleCacheMetric[_CurveResult]):
    """Streaming binary precision-recall curve (sample-cache state)."""

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_cache_state("inputs")
        self._add_cache_state("targets")

    def update(self, input, target) -> "BinaryPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        _binary_precision_recall_curve_update_input_check(input, target)
        self.inputs.append(input)
        self.targets.append(target)
        return self

    def compute(self) -> _CurveResult:
        if not self.inputs:
            return jnp.empty((0,)), jnp.empty((0,)), jnp.empty((0,))
        return binary_precision_recall_curve(
            self._concat_cache("inputs"), self._concat_cache("targets")
        )


class MulticlassPrecisionRecallCurve(
    SampleCacheMetric[Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]]
):
    """Streaming one-vs-all precision-recall curves per class."""

    def __init__(
        self, *, num_classes: Optional[int] = None, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.num_classes = num_classes
        self._add_cache_state("inputs")
        self._add_cache_state("targets")

    def update(self, input, target) -> "MulticlassPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        if self.num_classes is None and input.ndim == 2:
            self.num_classes = input.shape[1]
        _multiclass_precision_recall_curve_update_input_check(
            input, target, self.num_classes
        )
        self.inputs.append(input)
        self.targets.append(target)
        return self

    def compute(self):
        if not self.inputs:
            return [], [], []
        return multiclass_precision_recall_curve(
            jnp.concatenate(self.inputs, axis=0),
            self._concat_cache("targets"),
            num_classes=self.num_classes,
        )

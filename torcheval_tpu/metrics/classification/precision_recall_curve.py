"""PRC class metrics. Reference:
``torcheval/metrics/classification/precision_recall_curve.py:29-220``.

ISSUE 13: both classes grow an opt-in ``approx=`` mode
(``torcheval_tpu.sketch``) — the unbounded sample cache becomes a staging
buffer folded into resident fixed-size ``(tp, fp)`` bucket histograms, and
``compute()`` returns the curve over the NONEMPTY buckets with the bucket
representatives as thresholds (one point per occupied bucket — a
data-adaptive cousin of the binned PRC family, with the sketch's documented
relative-error threshold placement and exact cross-bucket counts). Memory
is O(buckets) regardless of stream length; merges are exact bucket adds.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_update_input_check,
    _multiclass_precision_recall_curve_update_input_check,
    binary_precision_recall_curve,
    multiclass_precision_recall_curve,
)
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.sketch import (
    DEFAULT_BUCKET_BITS,
    DEFAULT_MC_BUCKET_BITS,
    ScoreSketchCacheMixin,
    resolve_approx,
)
from torcheval_tpu.sketch.cache import (
    raise_sketch_overflow,
    sketch_mc_prc_from_parts,
    sketch_prc_from_parts,
)
from torcheval_tpu.sketch.histogram import trim_hist_curve
from torcheval_tpu.utils.devices import DeviceLike
from torcheval_tpu.utils.telemetry import log_once

_CurveResult = Tuple[jax.Array, jax.Array, jax.Array]


class BinaryPrecisionRecallCurve(
    ScoreSketchCacheMixin, SampleCacheMetric[_CurveResult]
):
    """Streaming binary precision-recall curve (sample-cache state; with
    ``approx=``, resident-sketch state — see the module docstring)."""

    def __init__(self, *, approx=None, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_cache_state("inputs")
        self._add_cache_state("targets")
        bits = resolve_approx(approx, default_bits=DEFAULT_BUCKET_BITS)
        if bits is not None:
            self._init_score_sketch(bits)

    def update(self, input, target) -> "BinaryPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        _binary_precision_recall_curve_update_input_check(input, target)
        self.inputs.append(input)
        self.targets.append(target)
        if self._sketch_enabled():
            self._score_sketch_stage(input.shape[0])
        return self

    def compute(self) -> _CurveResult:
        if self._sketch_enabled():
            precision, recall, nonempty, nan, overflow = (
                sketch_prc_from_parts(
                    *self._score_sketch_parts(), self._sketch_bits
                )
            )
            raise_sketch_overflow(overflow)
            self._sketch_check_nan(nan)
            return trim_hist_curve(
                precision, recall, nonempty, self._sketch_bits
            )
        if not self.inputs:
            return jnp.empty((0,)), jnp.empty((0,)), jnp.empty((0,))
        return binary_precision_recall_curve(
            self._concat_cache("inputs"), self._concat_cache("targets")
        )


class MulticlassPrecisionRecallCurve(
    ScoreSketchCacheMixin,
    SampleCacheMetric[Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]],
):
    """Streaming one-vs-all precision-recall curves per class (with
    ``approx=``, resident per-class sketches — requires ``num_classes`` at
    construction, which sizes the ``(C, B)`` histogram state)."""

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        approx=None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        self.num_classes = num_classes
        self._add_cache_state("inputs")
        self._add_cache_state("targets")
        bits = resolve_approx(approx, default_bits=DEFAULT_MC_BUCKET_BITS)
        if bits is not None and num_classes is None:
            if approx is None:
                # env-driven opt-in cannot size the (C, B) state without
                # num_classes: stay exact, loudly, rather than raise inside
                # code that never mentioned approx
                log_once(
                    "mc_prc_approx_needs_num_classes",
                    "TORCHEVAL_TPU_APPROX is set but "
                    "MulticlassPrecisionRecallCurve was built without "
                    "num_classes; the sketch state cannot be sized, so "
                    "this metric stays exact. Pass num_classes= to opt in.",
                )
                bits = None
            else:
                raise ValueError(
                    "approx= requires num_classes at construction (it sizes "
                    "the per-class sketch state)."
                )
        if bits is not None:
            self._init_score_sketch(bits, num_classes=num_classes)

    def update(self, input, target) -> "MulticlassPrecisionRecallCurve":
        input, target = self._input(input), self._input(target)
        if self.num_classes is None and input.ndim == 2:
            self.num_classes = input.shape[1]
        _multiclass_precision_recall_curve_update_input_check(
            input, target, self.num_classes
        )
        self.inputs.append(input)
        self.targets.append(target)
        if self._sketch_enabled():
            self._score_sketch_stage(input.shape[0])
        return self

    def compute(self):
        if self._sketch_enabled():
            precision, recall, nonempty, nan, overflow = (
                sketch_mc_prc_from_parts(
                    *self._score_sketch_parts(),
                    self._sketch_bits,
                    self.num_classes,
                )
            )
            raise_sketch_overflow(overflow)
            self._sketch_check_nan(nan, "per-class score entry(ies)")
            precisions, recalls, thresholds = [], [], []
            for c in range(self.num_classes):
                pc, rc, tc = trim_hist_curve(
                    precision[c], recall[c], nonempty[c], self._sketch_bits
                )
                precisions.append(pc)
                recalls.append(rc)
                thresholds.append(tc)
            return precisions, recalls, thresholds
        if not self.inputs:
            return [], [], []
        return multiclass_precision_recall_curve(
            jnp.concatenate(self.inputs, axis=0),
            self._concat_cache("targets"),
            num_classes=self.num_classes,
        )

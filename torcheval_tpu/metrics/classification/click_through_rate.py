"""ClickThroughRate and its windowed variant.

Extensions beyond the reference snapshot (see the functional module's note).
``ClickThroughRate`` is **deferred** (``metrics/deferred.py``): updates
append and the weighted-count fold runs in the shared one-program-per-window
pipeline. ``WindowedClickThroughRate`` stays eager — its deque window must
observe every update as its own ``(clicks, weight)`` row, which a bulk fold
would collapse; the window is a ``deque(maxlen=window_size)`` of per-update
rows, so the base class's deque machinery (state-dict round trips preserving
``maxlen``, object-lane sync, merge bounded by the window) carries a real
metric, not just the test dummies. Window mechanics live in
:mod:`._windowed` (shared with the calibration variant).
"""

from __future__ import annotations

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.classification._windowed import WindowedStateMixin
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.classification.click_through_rate import (
    _click_through_rate_update,
    _ctr_fold,
    _ctr_input_check,
    _ctr_compute,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.devices import DeviceLike


from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_num_tasks as _check_num_tasks,
)


def _fold_ctr(metric, input, weights):
    """Place inputs, run the fold, normalize to the ``(num_tasks,)`` axis
    (the fold reduces to scalars at ``num_tasks=1``) — the eager helper the
    windowed class still uses per update."""
    input = metric._input(input)
    if weights is not None and hasattr(weights, "shape"):
        weights = metric._input(weights)
    clicks, total = _click_through_rate_update(input, metric.num_tasks, weights)
    return (
        jnp.reshape(clicks, (metric.num_tasks,)),
        jnp.reshape(total, (metric.num_tasks,)),
    )


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py). Weighted updates
# defer the weights as a second chunk column, so the trailing statics are
# parsed by arity: rest == (num_tasks,) or (weights, num_tasks).
def _ctr_deferred_fold(input, *rest):
    num_tasks = rest[-1]
    weights = rest[0] if len(rest) == 2 else 1.0
    clicks, total = _ctr_fold(input, as_jax(weights))
    return {
        "click_total": jnp.reshape(clicks, (num_tasks,)),
        "weight_total": jnp.reshape(total, (num_tasks,)),
    }


class ClickThroughRate(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming weighted click-through rate.

    ``compute()`` returns ``sum(w * clicks) / sum(w)`` with shape
    ``(num_tasks,)`` (``0.0`` per task before any weighted update).
    """

    _fold_fn = staticmethod(_ctr_deferred_fold)
    _fold_per_chunk = True
    # pure terminal compute (safe_div) riding the window-step program;
    # update validation stays eager (it branches on the weights argument)
    _compute_fn = staticmethod(_ctr_compute)

    def __init__(
        self, *, num_tasks: int = 1, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        _check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        for name in ("click_total", "weight_total"):
            self._add_state(
                name,
                zeros_state((num_tasks,), dtype=jnp.float32),
                reduction=Reduction.SUM,
            )
        self._init_deferred()
        self._fold_params = (num_tasks,)

    def update(
        self,
        input,
        weights: Union[float, int, jax.Array, None] = None,
    ) -> "ClickThroughRate":
        input = self._input(input)
        if weights is None:
            _ctr_input_check(input, self.num_tasks, None)
            self._defer(input)
            return self
        # scalar weights become a 0-d column (broadcast in the fold);
        # array-likes (incl. python lists) are placed like any batch arg
        if isinstance(weights, (int, float)):
            weights = as_jax(weights)
        else:
            weights = self._input(weights)
        _ctr_input_check(
            input, self.num_tasks, weights if weights.ndim else None
        )
        self._defer(input, weights)
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(
        self, metrics: Iterable["ClickThroughRate"]
    ) -> "ClickThroughRate":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.click_total = self.click_total + jax.device_put(
                metric.click_total, self.device
            )
            self.weight_total = self.weight_total + jax.device_put(
                metric.weight_total, self.device
            )
        return self


class WindowedClickThroughRate(
    WindowedStateMixin, Metric[Tuple[jax.Array, jax.Array]]
):
    """CTR over the last ``window_size`` updates, optionally with lifetime.

    The window state is a ``deque(maxlen=window_size)`` of per-update
    ``(2, num_tasks)`` rows ``[clicks, weight]`` — the oldest update falls
    out automatically. ``merge_state`` appends the other replicas' windows
    after this one's (most recent entries win the bounded window); the
    lifetime counters merge by sum. Replicas must share the same window
    configuration to merge.

    ``compute()`` returns ``(lifetime_ctr, windowed_ctr)`` when
    ``enable_lifetime`` (default), else just the windowed rate; each has
    shape ``(num_tasks,)``.
    """

    _LIFETIME_STATES = ("click_total", "weight_total")

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        window_size: int = 100,
        enable_lifetime: bool = True,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        self.enable_lifetime = enable_lifetime
        if enable_lifetime:
            for name in self._LIFETIME_STATES:
                self._add_state(
                    name,
                    zeros_state((num_tasks,), dtype=jnp.float32),
                    reduction=Reduction.SUM,
                )
        self._init_window(window_size)

    def update(
        self,
        input,
        weights: Union[float, int, jax.Array, None] = None,
    ) -> "WindowedClickThroughRate":
        clicks, total = _fold_ctr(self, input, weights)
        if self.enable_lifetime:
            self.click_total = self.click_total + clicks
            self.weight_total = self.weight_total + total
        self._push_window(clicks, total)
        return self

    def compute(self):
        clicks, total = self._window_totals()
        windowed = _ctr_compute(clicks, total)
        if not self.enable_lifetime:
            return windowed
        return _ctr_compute(self.click_total, self.weight_total), windowed

    def merge_state(
        self, metrics: Iterable["WindowedClickThroughRate"]
    ) -> "WindowedClickThroughRate":
        self._merge_windowed(metrics)
        return self

"""ClickThroughRate and its windowed variant.

Extensions beyond the reference snapshot (see the functional module's note).
``WindowedClickThroughRate`` is a shipped deque-state metric: the window is
a ``deque(maxlen=window_size)`` of per-update ``(clicks, weight)`` rows, so
the base class's deque machinery (state-dict round trips preserving
``maxlen``, object-lane sync, merge bounded by the window) carries a real
metric, not just the test dummies. Window mechanics live in
:mod:`._windowed` (shared with the calibration variant).
"""

from __future__ import annotations

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.classification._windowed import WindowedStateMixin
from torcheval_tpu.metrics.functional.classification.click_through_rate import (
    _click_through_rate_update,
    _ctr_compute,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


from torcheval_tpu.metrics.functional.classification._task_shapes import (
    check_num_tasks as _check_num_tasks,
)


def _fold_ctr(metric, input, weights):
    """Place inputs, run the fold, normalize to the ``(num_tasks,)`` axis
    (the fold reduces to scalars at ``num_tasks=1``) — shared by the plain
    and windowed classes so the update contract cannot drift."""
    input = metric._input(input)
    if weights is not None and hasattr(weights, "shape"):
        weights = metric._input(weights)
    clicks, total = _click_through_rate_update(input, metric.num_tasks, weights)
    return (
        jnp.reshape(clicks, (metric.num_tasks,)),
        jnp.reshape(total, (metric.num_tasks,)),
    )


class ClickThroughRate(Metric[jax.Array]):
    """Streaming weighted click-through rate.

    ``compute()`` returns ``sum(w * clicks) / sum(w)`` with shape
    ``(num_tasks,)`` (``0.0`` per task before any weighted update).
    """

    def __init__(
        self, *, num_tasks: int = 1, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        _check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        for name in ("click_total", "weight_total"):
            self._add_state(
                name,
                zeros_state((num_tasks,), dtype=jnp.float32),
                reduction=Reduction.SUM,
            )

    def update(
        self,
        input,
        weights: Union[float, int, jax.Array, None] = None,
    ) -> "ClickThroughRate":
        clicks, total = _fold_ctr(self, input, weights)
        self.click_total = self.click_total + clicks
        self.weight_total = self.weight_total + total
        return self

    def compute(self) -> jax.Array:
        return _ctr_compute(self.click_total, self.weight_total)

    def merge_state(
        self, metrics: Iterable["ClickThroughRate"]
    ) -> "ClickThroughRate":
        for metric in metrics:
            self.click_total = self.click_total + jax.device_put(
                metric.click_total, self.device
            )
            self.weight_total = self.weight_total + jax.device_put(
                metric.weight_total, self.device
            )
        return self


class WindowedClickThroughRate(
    WindowedStateMixin, Metric[Tuple[jax.Array, jax.Array]]
):
    """CTR over the last ``window_size`` updates, optionally with lifetime.

    The window state is a ``deque(maxlen=window_size)`` of per-update
    ``(2, num_tasks)`` rows ``[clicks, weight]`` — the oldest update falls
    out automatically. ``merge_state`` appends the other replicas' windows
    after this one's (most recent entries win the bounded window); the
    lifetime counters merge by sum. Replicas must share the same window
    configuration to merge.

    ``compute()`` returns ``(lifetime_ctr, windowed_ctr)`` when
    ``enable_lifetime`` (default), else just the windowed rate; each has
    shape ``(num_tasks,)``.
    """

    _LIFETIME_STATES = ("click_total", "weight_total")

    def __init__(
        self,
        *,
        num_tasks: int = 1,
        window_size: int = 100,
        enable_lifetime: bool = True,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _check_num_tasks(num_tasks)
        self.num_tasks = num_tasks
        self.enable_lifetime = enable_lifetime
        if enable_lifetime:
            for name in self._LIFETIME_STATES:
                self._add_state(
                    name,
                    zeros_state((num_tasks,), dtype=jnp.float32),
                    reduction=Reduction.SUM,
                )
        self._init_window(window_size)

    def update(
        self,
        input,
        weights: Union[float, int, jax.Array, None] = None,
    ) -> "WindowedClickThroughRate":
        clicks, total = _fold_ctr(self, input, weights)
        if self.enable_lifetime:
            self.click_total = self.click_total + clicks
            self.weight_total = self.weight_total + total
        self._push_window(clicks, total)
        return self

    def compute(self):
        clicks, total = self._window_totals()
        windowed = _ctr_compute(clicks, total)
        if not self.enable_lifetime:
            return windowed
        return _ctr_compute(self.click_total, self.weight_total), windowed

    def merge_state(
        self, metrics: Iterable["WindowedClickThroughRate"]
    ) -> "WindowedClickThroughRate":
        self._merge_windowed(metrics)
        return self

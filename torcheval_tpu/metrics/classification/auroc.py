"""BinaryAUROC / BinaryAUPRC metrics. Reference:
``torcheval/metrics/classification/auroc.py:23-94``.

Sample-cache metrics: update appends the batch (O(1) host op, no device
work); all cost lives in ``compute()``'s single fused sort kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.auroc import (
    _auroc_update_input_check,
)
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.ops.curves import binary_auprc_kernel, binary_auroc_kernel
from torcheval_tpu.utils.devices import DeviceLike


class BinaryAUROC(SampleCacheMetric[jax.Array]):
    """Streaming area under the ROC curve (exact, sort-based).

    State is the full sample cache (reference design, ``auroc.py:55-71``);
    for bounded state use the binned PRC metrics instead.
    """

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_cache_state("inputs")
        self._add_cache_state("targets")

    def update(self, input, target) -> "BinaryAUROC":
        input, target = self._input(input), self._input(target)
        _auroc_update_input_check(input, target)
        self.inputs.append(input)
        self.targets.append(target)
        return self

    def compute(self) -> jax.Array:
        if not self.inputs:
            return jnp.asarray(0.5)
        return binary_auroc_kernel(
            self._concat_cache("inputs"), self._concat_cache("targets")
        )


class BinaryAUPRC(SampleCacheMetric[jax.Array]):
    """Streaming area under the PR curve (average precision).

    Framework extension (not in the reference snapshot v0.0.3; required by
    BASELINE.md config 2)."""

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_cache_state("inputs")
        self._add_cache_state("targets")

    def update(self, input, target) -> "BinaryAUPRC":
        input, target = self._input(input), self._input(target)
        _auroc_update_input_check(input, target)
        self.inputs.append(input)
        self.targets.append(target)
        return self

    def compute(self) -> jax.Array:
        if not self.inputs:
            return jnp.asarray(0.0)
        return binary_auprc_kernel(
            self._concat_cache("inputs"), self._concat_cache("targets")
        )

"""BinaryAUROC / BinaryAUPRC metrics. Reference:
``torcheval/metrics/classification/auroc.py:23-94``.

Sample-cache metrics: update appends the batch (O(1) host op, no device
work). With the default configuration all cost lives in ``compute()``'s
single fused sort kernel, exactly like the reference. For the 1B-sample
regime (BASELINE north star) pass ``compaction_threshold``: once the raw
cache holds that many samples it is folded into a bounded **exact**
per-unique-threshold summary (``ops/summary.py``) — sized by the stream's
score cardinality (distinct values seen), not its sample count, so memory
stays ~constant while results remain bit-identical to the all-samples
sort.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.auroc import (
    _auroc_update_input_check,
    _mc_average,
    _mc_curve_param_check,
)
from torcheval_tpu.metrics.functional.classification.precision_recall_curve import (
    _multiclass_precision_recall_curve_update_input_check,
)
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.ops.curves import (
    binary_auprc_counts_kernel,
    binary_auprc_counts_presorted_kernel,
    binary_auprc_kernel,
    binary_auroc_counts_kernel,
    binary_auroc_counts_presorted_kernel,
    binary_auroc_kernel,
    class_onehot_rows,
    multiclass_auprc_kernel,
    multiclass_auroc_kernel,
)
from torcheval_tpu.ops.summary import PAD_SCORE, compact_counts, compact_counts_fast
from torcheval_tpu.utils.devices import DeviceLike


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# compaction buffers pad to a multiple of 4M rows once past 4M (power of two
# below): bounds the compiled-shape count like pow2 rounding, but with <= 3.6%
# padding waste at the 1B bench's working size instead of pow2's worst-case
# ~2x (sorting pad rows is pure thrown-away bandwidth)
_PAD_GRANULE = 1 << 22


def _pad_cap(n: int) -> int:
    if n <= _PAD_GRANULE:
        return _next_pow2(n)
    return ((n + _PAD_GRANULE - 1) // _PAD_GRANULE) * _PAD_GRANULE


@jax.jit
def _combined_counts(raw_s, raw_t, sum_s, sum_tp, sum_fp):
    """Fold raw caches (unit counts) and summary caches (aggregated counts)
    into one (score, tp, fp) column set — traced as ONE program, so cache
    entries that are mesh-sharded global arrays stay on-device: XLA partitions
    the concat+sort pipeline and inserts the ICI collectives itself. No host
    ever touches shard data, which keeps this legal on multi-host meshes where
    most shards are non-addressable (SURVEY §2.7, VERDICT r1 missing #3)."""
    parts_s, parts_tp, parts_fp = [], [], []
    if raw_s:
        s = jnp.concatenate(raw_s)
        t = jnp.concatenate(raw_t).astype(jnp.int32)
        parts_s.append(s)
        parts_tp.append(t)
        parts_fp.append(1 - t)
    if sum_s:
        parts_s.append(jnp.concatenate(sum_s))
        parts_tp.append(jnp.concatenate(sum_tp))
        parts_fp.append(jnp.concatenate(sum_fp))
    return (
        jnp.concatenate(parts_s),
        jnp.concatenate(parts_tp),
        jnp.concatenate(parts_fp),
    )


@jax.jit
def _auroc_from_parts(raw_s, raw_t, sum_s, sum_tp, sum_fp):
    if not sum_s:
        # raw-only cache (no compaction yet): unit-count sort path moves
        # 8 bytes/row through the sort instead of 12 (ops/curves.py)
        return binary_auroc_kernel(
            jnp.concatenate(raw_s), jnp.concatenate(raw_t)
        )
    return binary_auroc_counts_kernel(
        *_combined_counts(raw_s, raw_t, sum_s, sum_tp, sum_fp)
    )


@jax.jit
def _auprc_from_parts(raw_s, raw_t, sum_s, sum_tp, sum_fp):
    if not sum_s:
        return binary_auprc_kernel(
            jnp.concatenate(raw_s), jnp.concatenate(raw_t)
        )
    return binary_auprc_counts_kernel(
        *_combined_counts(raw_s, raw_t, sum_s, sum_tp, sum_fp)
    )


# Streaming-compaction mode for the fold pipeline:
#   "auto"      — Pallas stream-compaction kernel on single-device TPU state,
#                 classic two-sort compact_counts elsewhere (CPU, sharded)
#   "off"       — always the two-sort path
#   "interpret" — kernel algorithm in Pallas interpret mode on any backend
#                 (CPU test suites exercise the integrated fast path with it)
STREAM_COMPACTION = "auto"


@partial(jax.jit, static_argnums=(6, 7))
def _compact_parts_fast(
    raw_s, raw_t, sum_s, sum_tp, sum_fp, nan_acc, cap: int, interpret: bool
):
    """:func:`_compact_parts` on the streaming-compaction pipeline: one sort
    + aggregation scans + the Pallas compress pass (``compact_counts_fast``)
    instead of two full sorts. Same contract; measured 1.5-1.8x at the 1B
    bench's fold sizes (docs/performance.md)."""
    s, tp, fp = _combined_counts(raw_s, raw_t, sum_s, sum_tp, sum_fp)
    n = s.shape[0]
    if cap > n:
        s = jnp.concatenate([s, jnp.full((cap - n,), PAD_SCORE, s.dtype)])
        tp = jnp.concatenate([tp, jnp.zeros((cap - n,), jnp.int32)])
        fp = jnp.concatenate([fp, jnp.zeros((cap - n,), jnp.int32)])
    s, tp, fp, n_unique, nan_dropped = compact_counts_fast(
        s, tp, fp, interpret=interpret
    )
    return s, tp, fp, n_unique, nan_acc + nan_dropped


@partial(jax.jit, static_argnums=6)
def _compact_parts(raw_s, raw_t, sum_s, sum_tp, sum_fp, nan_acc, cap: int):
    """Fold + pad-to-cap + compact in ONE traced program (cold path, but a
    single dispatch keeps sharded caches on the mesh end to end).

    Returns ``(s, tp, fp, n_unique, nan_acc')``. The NaN-sample count folds
    into a device-side accumulator instead of being host-checked here: round
    2's ``int(nan_dropped)`` read per compaction cost a tunnel RTT and a
    pipeline drain each time; the flag is now raised once, at ``compute()``.
    """
    s, tp, fp = _combined_counts(raw_s, raw_t, sum_s, sum_tp, sum_fp)
    n = s.shape[0]
    if cap > n:
        s = jnp.concatenate([s, jnp.full((cap - n,), PAD_SCORE, s.dtype)])
        tp = jnp.concatenate([tp, jnp.zeros((cap - n,), jnp.int32)])
        fp = jnp.concatenate([fp, jnp.zeros((cap - n,), jnp.int32)])
    s, tp, fp, n_unique, nan_dropped = compact_counts(s, tp, fp)
    return s, tp, fp, n_unique, nan_acc + nan_dropped


# ----------------------------------------------- multiclass summary helpers
def _mc_combined_counts(raw_s, raw_t, sum_s, sum_tp, sum_fp, num_classes):
    """Fold raw ``(N, C)`` caches and ``(K, C)`` per-class summaries into
    ``(C, M)`` count columns — the one-vs-all generalisation of
    :func:`_combined_counts`, one traced program (sharded caches stay on
    the mesh)."""
    parts_s, parts_tp, parts_fp = [], [], []
    if raw_s:
        x = jnp.concatenate(raw_s, axis=0)  # (N, C)
        t = jnp.concatenate(raw_t)
        onehot = class_onehot_rows(t, num_classes).astype(jnp.int32)  # (C, N)
        parts_s.append(x.T)
        parts_tp.append(onehot)
        parts_fp.append(1 - onehot)
    if sum_s:
        parts_s.append(jnp.concatenate(sum_s, axis=0).T)  # (C, K)
        parts_tp.append(jnp.concatenate(sum_tp, axis=0).T)
        parts_fp.append(jnp.concatenate(sum_fp, axis=0).T)
    return (
        jnp.concatenate(parts_s, axis=1),
        jnp.concatenate(parts_tp, axis=1),
        jnp.concatenate(parts_fp, axis=1),
    )


@partial(jax.jit, static_argnums=(6, 7))
def _mc_compact_parts(
    raw_s, raw_t, sum_s, sum_tp, sum_fp, nan_acc, cap: int, num_classes: int
):
    """Per-class compaction in one traced program: the binary
    :func:`_compact_parts` vmapped over the class axis. Returns ``(K, C)``
    summary columns (rows = threshold entries, so CAT state concatenation
    and the sync wire keep axis-0 semantics), the max per-class unique
    count (for the adaptive trim) and the accumulated NaN-sample counter."""
    s, tp, fp = _mc_combined_counts(
        raw_s, raw_t, sum_s, sum_tp, sum_fp, num_classes
    )
    n = s.shape[1]
    if cap > n:
        pad = cap - n
        s = jnp.concatenate(
            [s, jnp.full((num_classes, pad), PAD_SCORE, s.dtype)], axis=1
        )
        tp = jnp.concatenate(
            [tp, jnp.zeros((num_classes, pad), jnp.int32)], axis=1
        )
        fp = jnp.concatenate(
            [fp, jnp.zeros((num_classes, pad), jnp.int32)], axis=1
        )
    s2, tp2, fp2, nu, nan = jax.vmap(compact_counts)(s, tp, fp)
    return (
        s2.T,
        tp2.T,
        fp2.T,
        jnp.max(nu),
        nan_acc + jnp.sum(nan),
    )


@partial(jax.jit, static_argnums=5)
def _mc_auroc_from_parts(raw_s, raw_t, sum_s, sum_tp, sum_fp, num_classes):
    if not sum_s:
        return multiclass_auroc_kernel(
            jnp.concatenate(raw_s, axis=0), jnp.concatenate(raw_t)
        )
    s, tp, fp = _mc_combined_counts(
        raw_s, raw_t, sum_s, sum_tp, sum_fp, num_classes
    )
    return jax.vmap(binary_auroc_counts_kernel)(s, tp, fp)


@partial(jax.jit, static_argnums=5)
def _mc_auprc_from_parts(raw_s, raw_t, sum_s, sum_tp, sum_fp, num_classes):
    if not sum_s:
        return multiclass_auprc_kernel(
            jnp.concatenate(raw_s, axis=0), jnp.concatenate(raw_t)
        )
    s, tp, fp = _mc_combined_counts(
        raw_s, raw_t, sum_s, sum_tp, sum_fp, num_classes
    )
    return jax.vmap(binary_auprc_counts_kernel)(s, tp, fp)


class _CompactingCacheLifecycle:
    """Shared compaction lifecycle for sample-cache curve metrics (binary
    and multiclass): the threshold knob, the cache-row counter every state
    mutation must keep true, the deferred device-side NaN-sample flag, and
    the merge/reset/load hooks. Subclasses implement :meth:`_compact` (fold
    raw cache + summary into the bounded exact summary state) and register
    the ``inputs``/``targets``/``summary_*`` cache states plus the
    ``summary_nan_dropped`` SUM scalar via :meth:`_init_compaction`.
    """

    # what one unit of the NaN-dropped counter is, for the compute-time
    # error: the binary metrics count samples; the multiclass metrics count
    # per-class score entries (one bad (N, C) row can contribute up to C)
    _NAN_FLAG_NOUN = "sample(s)"

    # bucket_bits of the resident score sketch when the metric runs in
    # ``approx=`` mode (ISSUE 13), else None (exact unique-threshold
    # summaries). In approx mode ``compaction_threshold`` is reused as the
    # staging-cache fold cadence (default ``sketch.SKETCH_FOLD_ROWS``) and
    # ``_compact`` folds into fixed-size histograms instead of summaries.
    _sketch_bits: Optional[int] = None

    def _init_compaction(
        self,
        compaction_threshold: Optional[int],
        *,
        approx_bits: Optional[int] = None,
        sketch_classes: Optional[int] = None,
    ) -> None:
        if compaction_threshold is not None and compaction_threshold <= 0:
            raise ValueError(
                f"compaction_threshold must be positive or None, got "
                f"{compaction_threshold}."
            )
        self._sketch_bits = approx_bits
        self._sketch_classes = sketch_classes
        if approx_bits is not None and compaction_threshold is None:
            from torcheval_tpu.sketch.cache import SKETCH_FOLD_ROWS

            compaction_threshold = SKETCH_FOLD_ROWS
        self._compaction_threshold = compaction_threshold
        self._cached_samples = 0
        self._nan_checked = True  # no compactions yet -> nothing to check
        # True while the summary is known to be ONE buffer of per-threshold
        # unique rows in descending order with NaN padding last (every
        # _compact output is); merged/loaded state clears it until the next
        # compaction. Gates the sort-free presorted compute kernels.
        self._summary_sorted = True
        self._add_cache_state("inputs")
        self._add_cache_state("targets")
        if approx_bits is None:
            self._add_cache_state("summary_scores")
            self._add_cache_state("summary_tp")
            self._add_cache_state("summary_fp")
            # device-side count of NaN-scored samples that reached a
            # compaction; checked (and raised on) at compute() instead of
            # per compaction
            self._add_state(
                "summary_nan_dropped",
                zeros_state((), dtype=jnp.int32),
                reduction=Reduction.SUM,
            )
        else:
            # resident sketch: fixed-size (tp, fp) bucket histograms. SUM
            # reduction IS the exact merge (bucket add), so sync /
            # merge_state / checkpoints need no new machinery; int32 counts
            # follow the repo exactness rule, fail-closed at the edge
            # (sketch/histogram.counts_exactness_flag). The schema has ONE
            # definition, shared with the PRC/value sketch mixins.
            from torcheval_tpu.sketch.cache import (
                register_score_sketch_states,
            )

            register_score_sketch_states(self, approx_bits, sketch_classes)

    def _sketch_enabled(self) -> bool:
        return self._sketch_bits is not None

    def _sketch_compact(self) -> None:
        """Approx-mode ``_compact``: fold the staged raw cache into the
        resident bucket histograms (one jitted program, no host reads —
        there is no adaptive trim to size; the sketch shape is static)."""
        from torcheval_tpu.sketch.cache import (
            _count_fold,
            mc_score_fold_parts,
            score_fold_parts,
        )

        if not self.inputs:
            self._cached_samples = 0
            return
        n = sum(int(a.shape[0]) for a in self.inputs)
        dist = self._sketch_sharded_mesh()
        if dist is not None:
            # mesh-sharded staging: ONE exact psum of per-shard histograms
            # consumes the resident format directly — no bucket exchange,
            # no re-bucketing, no per-sample traffic (ISSUE 13(c))
            from torcheval_tpu.ops.dist_curves import sharded_sketch_counts

            mesh, axis = dist
            tp, fp, nan = sharded_sketch_counts(
                self.inputs,
                self.targets,
                mesh=mesh,
                axis=str(axis),
                bucket_bits=self._sketch_bits,
                num_classes=self._sketch_classes,
            )
            _obs.counter(
                "ops.dist_curves.calls",
                path="sketch",
                family=(
                    "binary" if self._sketch_classes is None else "multiclass"
                ),
            )
            _count_fold(
                "score" if self._sketch_classes is None else "mc_score", n
            )
            self.inputs = []
            self.targets = []
            # psum outputs are mesh-replicated; device_put re-places them
            # on the metric's own device/sharding device-to-device (a host
            # round trip here would synchronize every fold — review
            # finding), then bucket-add into resident state
            self.sketch_tp = self.sketch_tp + jax.device_put(
                tp, self.device
            )
            self.sketch_fp = self.sketch_fp + jax.device_put(
                fp, self.device
            )
            self.sketch_nan_dropped = self.sketch_nan_dropped + jax.device_put(
                nan, self.device
            )
            self._cached_samples = 0
            return
        if self._sketch_classes is None:
            tp, fp, nan = score_fold_parts(
                self.inputs,
                self.targets,
                self.sketch_tp,
                self.sketch_fp,
                self.sketch_nan_dropped,
                self._sketch_bits,
            )
            _count_fold("score", n)
        else:
            tp, fp, nan = mc_score_fold_parts(
                self.inputs,
                self.targets,
                self.sketch_tp,
                self.sketch_fp,
                self.sketch_nan_dropped,
                self._sketch_bits,
                self._sketch_classes,
            )
            _count_fold("mc_score", n)
        self.inputs = []
        self.targets = []
        self.sketch_tp = tp
        self.sketch_fp = fp
        self.sketch_nan_dropped = nan
        self._cached_samples = 0

    def _sketch_value(self, from_parts, *extra_statics):
        """Dispatch an approx-mode compute program over (staged leftovers,
        resident sketch) — state untouched, so ``compute()`` stays
        idempotent — then raise the loud-NaN error AFTER the dispatch (the
        scalar read overlaps the kernel, the ``_check_nan_flag`` shape)."""
        *value, nan_total, overflow = from_parts(
            list(self.inputs),
            list(self.targets),
            self.sketch_tp,
            self.sketch_fp,
            self.sketch_nan_dropped,
            self._sketch_bits,
            *extra_statics,
        )
        from torcheval_tpu.sketch.cache import (
            raise_sketch_nan,
            raise_sketch_overflow,
        )

        raise_sketch_overflow(overflow)
        raise_sketch_nan(nan_total, self._NAN_FLAG_NOUN)
        return value[0] if len(value) == 1 else tuple(value)

    def _compact(self) -> None:
        raise NotImplementedError

    def _count_cached_update(self, n_rows: int) -> None:
        self._cached_samples += n_rows
        if (
            self._compaction_threshold is not None
            and self._cached_samples >= self._compaction_threshold
        ):
            self._compact()

    def _set_states(self, values) -> None:
        # ANY state installation (merge, load, toolkit sync via
        # clone+_set_states) may bring in a nonzero NaN flag from another
        # replica — a cached clean check must not survive it
        super()._set_states(values)
        if "summary_nan_dropped" in values or "sketch_nan_dropped" in values:
            self._nan_checked = False
        if any(k.startswith("summary_") for k in values):
            self._summary_sorted = False  # unknown provenance

    def _install_compacted(self, s, tp, fp, n_unique, nan_acc) -> None:
        """Install a ``_compact`` program's output: prefetch the adaptive
        trim's one host read (``copy_to_host_async`` overlaps it with the
        compaction kernel itself), fold the NaN counter, trim to the padded
        unique count, and swap the five cache states."""
        try:
            n_unique.copy_to_host_async()
        except AttributeError:
            pass
        self.summary_nan_dropped = nan_acc
        self._nan_checked = False
        keep = min(s.shape[0], _pad_cap(max(int(n_unique), 1)))
        self.inputs = []
        self.targets = []
        self.summary_scores = [s[:keep]]
        self.summary_tp = [tp[:keep]]
        self.summary_fp = [fp[:keep]]
        self._cached_samples = 0
        # every compaction path emits unique rows, descending, padding last
        self._summary_sorted = True

    def _check_nan_flag(self) -> None:
        """Raise (uniformly, at compute time) if NaN-scored samples ever
        reached a compaction. One host read of an int32 scalar, skipped when
        no compaction has happened since the last check."""
        if self._nan_checked:
            return
        dropped = int(self.summary_nan_dropped)
        # only a CLEAN check is cached: poisoned state must keep raising on
        # every compute, not just the first (an eval loop that swallows one
        # error must not silently get NaN-dropped results afterwards)
        self._nan_checked = dropped == 0
        if dropped:
            raise ValueError(
                f"{dropped} {self._NAN_FLAG_NOUN} with NaN scores reached "
                "compaction; "
                "NaN is the summary padding sentinel and such samples cannot "
                "be represented (the uncompacted metric would count them). "
                "Filter NaNs before update() or use "
                "compaction_threshold=None."
            )

    def _prepare_for_merge_state(self) -> None:
        # compacting metrics ship their bounded summary (one buffer per
        # state), not the raw cache; reference hook semantics
        # (metric.py:112-121)
        if self._compaction_threshold is not None:
            self._compact()
        super()._prepare_for_merge_state()

    # -------------------------------------------- cache-counter maintenance
    # every path that rewrites the raw cache must keep _cached_samples true,
    # or merge-fed accumulators would never compact (unbounded growth) and
    # reset metrics would compact spuriously
    def _recount_cache(self) -> None:
        self._cached_samples = sum(int(a.shape[0]) for a in self.inputs)
        if self._compaction_threshold is None:
            return
        if self._sketch_bits is not None:
            # approx mode: the raw cache is a staging buffer; fold when the
            # cadence is exceeded (the resident sketch never re-triggers —
            # its size is static)
            if self._cached_samples >= self._compaction_threshold:
                self._compact()
            return
        # compact when raw rows exceed the threshold, OR when merges have
        # fragmented the summary into multiple buffers past the threshold —
        # merge-fed accumulators receiving already-compacted sources must
        # stay bounded too. A single (post-compaction) summary buffer never
        # re-triggers, so this cannot loop.
        summary_rows = sum(int(a.shape[0]) for a in self.summary_scores)
        if self._cached_samples >= self._compaction_threshold or (
            len(self.summary_scores) > 1
            and summary_rows >= self._compaction_threshold
        ):
            self._compact()

    def merge_state(self, metrics):
        metrics = list(metrics)
        self._summary_sorted = False  # concatenated segments may overlap
        # (the recount below may re-compact, legitimately restoring it)
        super().merge_state(metrics)
        if self._sketch_bits is not None:
            # the cache base merges only list states; the sketch arrays are
            # additive across replicas — bucket add IS the exact merge
            # (ISSUE 13 acceptance: merged == single-stream bit-identical,
            # integer adds). One shared definition with the mixins.
            from torcheval_tpu.sketch.cache import merge_score_sketch_states

            merge_score_sketch_states(self, metrics)
        else:
            for metric in metrics:
                # the scalar NaN flag is additive across replicas
                self.summary_nan_dropped = (
                    self.summary_nan_dropped
                    + jax.device_put(metric.summary_nan_dropped, self.device)
                )
        self._nan_checked = False
        self._recount_cache()
        return self

    def reset(self):
        super().reset()
        self._cached_samples = 0
        self._nan_checked = True  # flag state re-zeroed by reset
        self._summary_sorted = True  # empty summary is trivially sorted
        return self

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        self._summary_sorted = False  # unknown provenance
        super().load_state_dict(state_dict, strict)
        self._nan_checked = False  # loaded state may carry a nonzero flag
        self._recount_cache()

    # --------------------------------------------- distributed curve path
    def _sharded_raw_mesh(self):
        """``(mesh, axis)`` when the whole cache is raw entries sharded
        along ONE named mesh axis (the
        :class:`~torcheval_tpu.parallel.ShardedEvaluator` regime) — the
        distributed bucket-sort curve path applies (``ops/dist_curves.py``);
        else ``None`` (single-device, replicated, mixed-summary, or
        uneven-shard caches keep the fused sort program, whose partitioning
        XLA handles).

        The axis may be a SUBSET of a multi-axis mesh: a (data, model)
        topology with the cache sharded over ``data`` runs the bucket sort
        over the data axis and replicates the scalar result over ``model``
        (the kernels size themselves from ``mesh.shape[axis]``). What still
        falls back: a tuple spec entry (rows sharded over several axes at
        once), a sharded trailing dim (per-class score columns must stay
        local to a shard), and row counts not divisible by the axis."""
        if self._sketch_bits is not None:
            return None  # approx compute owns its own (sketch-psum) path
        if self.summary_scores or not self.inputs:
            return None
        return self._uniform_cache_mesh()

    def _sketch_sharded_mesh(self):
        """Approx-mode twin of :meth:`_sharded_raw_mesh`: ``(mesh, axis)``
        when the STAGING cache is uniformly sharded — the resident-sketch
        fold then runs as one ``shard_map`` psum of fixed-size histograms
        (``ops/dist_curves.sharded_sketch_counts``) instead of pulling
        shards to one device."""
        if not self.inputs:
            return None
        return self._uniform_cache_mesh()

    def _uniform_cache_mesh(self):
        from jax.sharding import NamedSharding

        mesh = axis = None
        for a in list(self.inputs) + list(self.targets):
            sh = getattr(a, "sharding", None)
            if not isinstance(sh, NamedSharding):
                return None
            spec = sh.spec
            if (
                not spec
                or not isinstance(spec[0], str)
                or any(s is not None for s in spec[1:])
                or sh.mesh.shape[spec[0]] <= 1
                or a.shape[0] % sh.mesh.shape[spec[0]]
            ):
                return None
            if mesh is None:
                mesh, axis = sh.mesh, spec[0]
            elif sh.mesh != mesh or spec[0] != axis:
                return None
        return mesh, axis

    def _sharded_value(self, kernel):
        """Run a distributed curve kernel over the sharded cache; ``None``
        when the cache is not uniformly sharded or the score distribution
        overloaded a bucket (exact overflow detection — fall back to the
        gather-based sort program rather than lose rows)."""
        dist = self._sharded_raw_mesh()
        if dist is None:
            return None
        mesh, axis = dist
        value, overflow = kernel(
            self.inputs, self.targets, mesh=mesh, axis=str(axis)
        )
        if int(overflow):
            return None
        return value


class _BinaryCurveMetric(_CompactingCacheLifecycle, SampleCacheMetric[jax.Array]):
    """Shared cache + compaction machinery for the binary curve metrics.

    State is five CAT caches: raw ``inputs``/``targets`` plus a summary of
    (score, tp, fp) columns — ``summary_scores`` (float, ``NaN`` padding)
    and ``summary_tp``/``summary_fp`` (int32 counts — exact while the
    stream's TOTAL positives and negatives each stay below 2^31; see
    ``ops/summary.py``). CAT reduction is correct for the summary too:
    concatenated summaries (across replicas or processes) may repeat a
    threshold, and the weighted curve kernels merge tied scores by
    construction — no re-compaction is needed for correctness.

    With ``approx=`` (ISSUE 13: ``True`` = default bucket count, an int =
    bucket count, env ``TORCHEVAL_TPU_APPROX``), the summary states are
    replaced by a RESIDENT fixed-size score sketch — ``sketch_tp`` /
    ``sketch_fp`` bucket histograms (``torcheval_tpu.sketch``) — giving
    O(buckets) memory forever regardless of stream length or score
    cardinality, exact (bucket-add) merges, and a documented error bound
    (``sketch.auroc_error_bound`` / ``auprc_error_bound``, computable from
    the sketch itself). ``compaction_threshold`` then sets the staging-fold
    cadence (default ``sketch.SKETCH_FOLD_ROWS``).
    """

    def __init__(
        self,
        *,
        compaction_threshold: Optional[int] = None,
        approx=None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        from torcheval_tpu.sketch import DEFAULT_BUCKET_BITS, resolve_approx

        self._init_compaction(
            compaction_threshold,
            approx_bits=resolve_approx(
                approx, default_bits=DEFAULT_BUCKET_BITS
            ),
        )

    def update(self, input, target) -> "_BinaryCurveMetric":
        input, target = self._input(input), self._input(target)
        _auroc_update_input_check(input, target)
        self.inputs.append(input)
        self.targets.append(target)
        self._count_cached_update(input.shape[0])
        return self

    # ------------------------------------------------------------ compaction
    def _compact(self) -> None:
        if self._sketch_bits is not None:
            return self._sketch_compact()
        return self._summary_compact()

    def _summary_compact(self) -> None:
        """Fold raw cache + summary into one padded unique-threshold summary.

        One jitted program (fold + pad + compact); the buffer is padded to a
        4M-row granule (pow2 below that) so XLA compiles a bounded set of
        shapes over a metric's lifetime, not one per chunk size.

        The one remaining host read — ``int(n_unique)`` for the adaptive trim
        that keeps low-cardinality streams on small buffers — is prefetched
        with ``copy_to_host_async`` immediately after dispatch, so it costs
        the compaction kernel's own latency (which any consumer of the
        summary pays regardless), not an extra tunnel round trip on top. The
        NaN-sample check that used to be a second host read per compaction is
        a device-side accumulator raised at :meth:`compute`.
        """
        n = sum(int(a.shape[0]) for a in self.inputs) + sum(
            int(a.shape[0]) for a in self.summary_scores
        )
        if n == 0:
            return
        mode = self._stream_compaction_mode()
        if mode is None:
            s, tp, fp, n_unique, nan_acc = _compact_parts(
                self.inputs,
                self.targets,
                self.summary_scores,
                self.summary_tp,
                self.summary_fp,
                self.summary_nan_dropped,
                _pad_cap(n),
            )
        else:
            s, tp, fp, n_unique, nan_acc = _compact_parts_fast(
                self.inputs,
                self.targets,
                self.summary_scores,
                self.summary_tp,
                self.summary_fp,
                self.summary_nan_dropped,
                _pad_cap(n),
                mode,  # interpret flag
            )
        self._install_compacted(s, tp, fp, n_unique, nan_acc)

    def _stream_compaction_mode(self):
        """None -> classic two-sort path; False -> Pallas kernel (compiled);
        True -> Pallas kernel in interpret mode. Kernel requires
        single-device state (no GSPMD rule yet — sharded caches keep the
        sort path, whose partitioning XLA already handles)."""
        if STREAM_COMPACTION == "off":
            return None
        if STREAM_COMPACTION == "interpret":
            return True
        dev = self._device
        if isinstance(dev, jax.Device) and dev.platform == "tpu":
            return False
        return None

    def _presorted_summary(self):
        """``(s, tp, fp)`` when state is ALREADY a single summary buffer
        known to be sorted-unique, else ``None``. Gated to the same mode as
        the streaming compaction so CPU/sharded behavior (one fused
        fold+sort program at compute) is unchanged.

        Raw leftovers make this return ``None`` rather than force a
        compaction: a compute-time compaction is the fused sort PLUS the
        compress pass and state install, strictly more work than feeding
        the leftovers straight to the sorting counts kernel — measured
        60 vs 74M preds/s on the 100M bench leg (the round-4/5 "100M
        regression": the forced fold, not the kernel). The sort-free path
        pays off exactly when the stream ended on a compaction boundary."""
        if (
            self._compaction_threshold is None
            or self._stream_compaction_mode() is None
        ):
            return None
        if (
            not self._summary_sorted
            or self.inputs
            or len(self.summary_scores) != 1
        ):
            return None
        return (
            self.summary_scores[0],
            self.summary_tp[0],
            self.summary_fp[0],
        )


class BinaryAUROC(_BinaryCurveMetric):
    """Streaming area under the ROC curve (exact, sort-based).

    By default state is the full sample cache (reference design,
    ``auroc.py:55-71``); with ``compaction_threshold`` set, state is a
    bounded exact unique-threshold summary. For fixed-size approximate state
    use the binned PRC metrics instead.

    Mesh-sharded caches (via :class:`~torcheval_tpu.parallel.ShardedEvaluator`)
    compute in one SPMD program — see :func:`_combined_counts`.
    """

    def compute(self) -> jax.Array:
        if self._sketch_bits is not None:
            from torcheval_tpu.sketch.cache import sketch_auroc_from_parts

            return self._sketch_value(sketch_auroc_from_parts)
        if not (self.inputs or self.summary_scores):
            return jnp.asarray(0.5)
        from torcheval_tpu.ops.dist_curves import sharded_binary_auroc

        # mesh-sharded raw cache: distributed bucket sort — one all_to_all
        # of the rows instead of XLA's per-partition operand gather
        result = self._sharded_value(sharded_binary_auroc)
        _obs.counter(
            "ops.dist_curves.calls",
            path="dist" if result is not None else "fused",
            family="binary",
        )
        if result is None:
            presorted = self._presorted_summary()
            if presorted is not None:
                # known-sorted unique summary: cumsums + trapezoid, no sort
                result = binary_auroc_counts_presorted_kernel(*presorted)
            else:
                result = _auroc_from_parts(
                    self.inputs,
                    self.targets,
                    self.summary_scores,
                    self.summary_tp,
                    self.summary_fp,
                )
        # after dispatching the curve kernel, so the flag read (one host
        # scalar) overlaps with it instead of stalling in front of it
        self._check_nan_flag()
        return result


@jax.jit
def _mc_auroc_presorted(s, tp, fp):
    """Per-class AUROC over ``(K, C)`` summary columns already sorted-unique
    per class (the ``_mc_compact_parts`` invariant): cumsums + trapezoid,
    no compute-time sort — the multiclass twin of
    :func:`binary_auroc_counts_presorted_kernel`."""
    return jax.vmap(binary_auroc_counts_presorted_kernel)(s.T, tp.T, fp.T)


@jax.jit
def _mc_auprc_presorted(s, tp, fp):
    return jax.vmap(binary_auprc_counts_presorted_kernel)(s.T, tp.T, fp.T)


class _MulticlassCurveMetric(
    _CompactingCacheLifecycle, SampleCacheMetric[jax.Array]
):
    """Shared cache + compaction for the one-vs-all multiclass curve metrics.

    Framework extensions modelled on later torcheval releases: state is the
    raw ``(N, C)`` score / ``(N,)`` label cache (the binary metrics' default
    design); compute runs the binary curve kernel ``vmap``-ed over classes.

    With ``compaction_threshold`` set, the raw cache folds into per-class
    exact unique-threshold summaries — the binary machinery vmapped over the
    class axis (:func:`_mc_compact_parts`). Summary state is ``(K, C)``
    columns (rows = threshold entries, so CAT merges stay axis-0) at 12·C
    bytes per unique threshold row, where K is the max per-class score
    CARDINALITY of the stream — not the sample count. Typical model heads
    emit far fewer distinct values than samples (a bf16 pipeline at most
    2^16); the float32 worst case over [0, 1) is ~2^30, so the bound is the
    stream's score granularity, vs the unconditionally unbounded 4·(C+1)
    bytes *per sample* of the raw cache (round-4 verdict weak #6: the
    ImageNet/1B-scale story OOMs without this).
    """

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "macro",
        compaction_threshold: Optional[int] = None,
        approx=None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        from torcheval_tpu.sketch import (
            DEFAULT_MC_BUCKET_BITS,
            resolve_approx,
        )

        _mc_curve_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        self._init_compaction(
            compaction_threshold,
            approx_bits=resolve_approx(
                approx, default_bits=DEFAULT_MC_BUCKET_BITS
            ),
            sketch_classes=num_classes,
        )

    # one bad (N, C) row contributes one dropped ENTRY per NaN-scored class
    _NAN_FLAG_NOUN = "per-class score entry(ies)"

    def update(self, input, target):
        input, target = self._input(input), self._input(target)
        _multiclass_precision_recall_curve_update_input_check(
            input, target, self.num_classes
        )
        self.inputs.append(input)
        self.targets.append(target)
        self._count_cached_update(input.shape[0])
        return self

    def _compact(self) -> None:
        if self._sketch_bits is not None:
            return self._sketch_compact()
        return self._summary_compact()

    def _summary_compact(self) -> None:
        """Fold the raw cache + per-class summaries into one padded
        ``(K, C)`` summary set (one jitted program; same adaptive-trim
        host-read overlap as the binary :meth:`_BinaryCurveMetric._compact`)."""
        n = sum(int(a.shape[0]) for a in self.inputs) + sum(
            int(a.shape[0]) for a in self.summary_scores
        )
        if n == 0:
            return
        s, tp, fp, n_unique, nan_acc = _mc_compact_parts(
            self.inputs,
            self.targets,
            self.summary_scores,
            self.summary_tp,
            self.summary_fp,
            self.summary_nan_dropped,
            _pad_cap(n),
            self.num_classes,
        )
        self._install_compacted(s, tp, fp, n_unique, nan_acc)

    def _mc_presorted(self):
        """``(K, C)`` summary columns when state is ALREADY a single
        known-sorted buffer, else ``None``. Pure XLA — unlike the binary
        presorted path there is no Pallas gating, so it serves every
        backend. Raw leftovers disable it rather than force a compute-time
        compaction (see :meth:`_BinaryCurveMetric._presorted_summary`)."""
        if self._compaction_threshold is None:
            return None
        if (
            not self._summary_sorted
            or self.inputs
            or len(self.summary_scores) != 1
        ):
            return None
        return (
            self.summary_scores[0],
            self.summary_tp[0],
            self.summary_fp[0],
        )

    def _per_class(self, from_parts):
        result = from_parts(
            self.inputs,
            self.targets,
            self.summary_scores,
            self.summary_tp,
            self.summary_fp,
            self.num_classes,
        )
        self._check_nan_flag()
        return result


class MulticlassAUROC(_MulticlassCurveMetric):
    """Streaming one-vs-all multiclass AUROC (framework extension).

    Mesh-sharded raw caches take the distributed bucket-sort path with a
    shared per-class bucket exchange (``ops/dist_curves.py``) — no sample
    gather; see :meth:`_CompactingCacheLifecycle._sharded_raw_mesh`."""

    def compute(self) -> jax.Array:
        if self._sketch_bits is not None:
            from torcheval_tpu.sketch.cache import (
                sketch_mc_auroc_from_parts,
            )

            per_class = self._sketch_value(
                sketch_mc_auroc_from_parts, self.num_classes
            )
            return _mc_average(per_class, self.average)
        if not (self.inputs or self.summary_scores):
            return (
                jnp.asarray(0.5)
                if self.average == "macro"
                else jnp.full((self.num_classes,), 0.5)
            )
        from torcheval_tpu.ops.dist_curves import sharded_multiclass_auroc

        per_class = self._sharded_value(sharded_multiclass_auroc)
        _obs.counter(
            "ops.dist_curves.calls",
            path="dist" if per_class is not None else "fused",
            family="multiclass",
        )
        if per_class is not None:
            self._check_nan_flag()
        else:
            presorted = self._mc_presorted()
            if presorted is not None:
                per_class = _mc_auroc_presorted(*presorted)
                self._check_nan_flag()
            else:
                per_class = self._per_class(_mc_auroc_from_parts)
        return _mc_average(per_class, self.average)


class MulticlassAUPRC(_MulticlassCurveMetric):
    """Streaming one-vs-all multiclass average precision (framework
    extension). Sharded caches ride the same distributed path as
    :class:`MulticlassAUROC`."""

    def compute(self) -> jax.Array:
        if self._sketch_bits is not None:
            from torcheval_tpu.sketch.cache import (
                sketch_mc_auprc_from_parts,
            )

            per_class = self._sketch_value(
                sketch_mc_auprc_from_parts, self.num_classes
            )
            return _mc_average(per_class, self.average)
        if not (self.inputs or self.summary_scores):
            return (
                jnp.asarray(0.0)
                if self.average == "macro"
                else jnp.zeros((self.num_classes,))
            )
        from torcheval_tpu.ops.dist_curves import sharded_multiclass_auprc

        per_class = self._sharded_value(sharded_multiclass_auprc)
        _obs.counter(
            "ops.dist_curves.calls",
            path="dist" if per_class is not None else "fused",
            family="multiclass",
        )
        if per_class is not None:
            self._check_nan_flag()
        else:
            presorted = self._mc_presorted()
            if presorted is not None:
                per_class = _mc_auprc_presorted(*presorted)
                self._check_nan_flag()
            else:
                per_class = self._per_class(_mc_auprc_from_parts)
        return _mc_average(per_class, self.average)


class BinaryAUPRC(_BinaryCurveMetric):
    """Streaming area under the PR curve (average precision).

    Framework extension (not in the reference snapshot v0.0.3; required by
    BASELINE.md config 2)."""

    def compute(self) -> jax.Array:
        if self._sketch_bits is not None:
            from torcheval_tpu.sketch.cache import sketch_auprc_from_parts

            return self._sketch_value(sketch_auprc_from_parts)
        if not (self.inputs or self.summary_scores):
            return jnp.asarray(0.0)
        from torcheval_tpu.ops.dist_curves import sharded_binary_auprc

        result = self._sharded_value(sharded_binary_auprc)
        _obs.counter(
            "ops.dist_curves.calls",
            path="dist" if result is not None else "fused",
            family="binary",
        )
        if result is None:
            presorted = self._presorted_summary()
            if presorted is not None:
                result = binary_auprc_counts_presorted_kernel(*presorted)
            else:
                result = _auprc_from_parts(
                    self.inputs,
                    self.targets,
                    self.summary_scores,
                    self.summary_tp,
                    self.summary_fp,
                )
        self._check_nan_flag()
        return result

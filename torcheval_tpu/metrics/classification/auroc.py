"""BinaryAUROC / BinaryAUPRC metrics. Reference:
``torcheval/metrics/classification/auroc.py:23-94``.

Sample-cache metrics: update appends the batch (O(1) host op, no device
work). With the default configuration all cost lives in ``compute()``'s
single fused sort kernel, exactly like the reference. For the 1B-sample
regime (BASELINE north star) pass ``compaction_threshold``: once the raw
cache holds that many samples it is folded into a bounded **exact**
per-unique-threshold summary (``ops/summary.py``) — float32 scores admit at
most 2^24 distinct values per unit range, so memory stays ~constant while
results remain bit-identical to the all-samples sort.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.auroc import (
    _auroc_update_input_check,
)
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.ops.curves import (
    binary_auprc_counts_kernel,
    binary_auroc_counts_kernel,
)
from torcheval_tpu.ops.summary import PAD_SCORE, compact_counts
from torcheval_tpu.utils.devices import DeviceLike


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class _BinaryCurveMetric(SampleCacheMetric[jax.Array]):
    """Shared cache + compaction machinery for the binary curve metrics.

    State is five CAT caches: raw ``inputs``/``targets`` plus a summary of
    (score, tp, fp) columns — ``summary_scores`` (float, ``NaN`` padding)
    and ``summary_tp``/``summary_fp`` (int32 counts — exact while the
    stream's TOTAL positives and negatives each stay below 2^31; see
    ``ops/summary.py``). CAT reduction is correct for the summary too:
    concatenated summaries (across replicas or processes) may repeat a
    threshold, and the weighted curve kernels merge tied scores by
    construction — no re-compaction is needed for correctness.
    """

    def __init__(
        self,
        *,
        compaction_threshold: Optional[int] = None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        if compaction_threshold is not None and compaction_threshold <= 0:
            raise ValueError(
                f"compaction_threshold must be positive or None, got "
                f"{compaction_threshold}."
            )
        self._compaction_threshold = compaction_threshold
        self._cached_samples = 0
        self._add_cache_state("inputs")
        self._add_cache_state("targets")
        self._add_cache_state("summary_scores")
        self._add_cache_state("summary_tp")
        self._add_cache_state("summary_fp")

    def update(self, input, target) -> "_BinaryCurveMetric":
        input, target = self._input(input), self._input(target)
        _auroc_update_input_check(input, target)
        self.inputs.append(input)
        self.targets.append(target)
        self._cached_samples += input.shape[0]
        if (
            self._compaction_threshold is not None
            and self._cached_samples >= self._compaction_threshold
        ):
            self._compact()
        return self

    # ------------------------------------------------------------ compaction
    def _all_counts(self) -> Optional[Tuple[jax.Array, jax.Array, jax.Array]]:
        """Every cached row as (score, tp, fp) count columns: raw samples are
        unit counts, summary rows are pre-aggregated."""
        scores, tps, fps = [], [], []
        if self.inputs:
            s = jnp.concatenate(self.inputs)
            t = jnp.concatenate(self.targets).astype(jnp.int32)
            scores.append(s)
            tps.append(t)
            fps.append(1 - t)
        if self.summary_scores:
            scores.append(jnp.concatenate(self.summary_scores))
            tps.append(jnp.concatenate(self.summary_tp))
            fps.append(jnp.concatenate(self.summary_fp))
        if not scores:
            return None
        return (
            jnp.concatenate(scores),
            jnp.concatenate(tps),
            jnp.concatenate(fps),
        )

    def _compact(self) -> None:
        """Fold raw cache + summary into one padded unique-threshold summary.

        The buffer is padded to the next power of two so XLA compiles O(log)
        distinct shapes over a metric's lifetime, not one per chunk size.
        """
        counts = self._all_counts()
        if counts is None:
            return
        s, tp, fp = counts
        n = s.shape[0]
        cap = _next_pow2(n)
        if cap > n:
            s = jnp.concatenate([s, jnp.full((cap - n,), PAD_SCORE, s.dtype)])
            tp = jnp.concatenate([tp, jnp.zeros((cap - n,), jnp.int32)])
            fp = jnp.concatenate([fp, jnp.zeros((cap - n,), jnp.int32)])
        s, tp, fp, n_unique = compact_counts(s, tp, fp)
        # trim to the tightest power of two that holds the unique rows, so a
        # low-cardinality stream keeps a small buffer (host sync once per
        # compaction — the cold path)
        keep = min(cap, _next_pow2(max(int(n_unique), 1)))
        self.inputs = []
        self.targets = []
        self.summary_scores = [s[:keep]]
        self.summary_tp = [tp[:keep]]
        self.summary_fp = [fp[:keep]]
        self._cached_samples = 0

    def _prepare_for_merge_state(self) -> None:
        # compacting metrics ship their bounded summary (one buffer per
        # state), not the raw cache; reference hook semantics
        # (metric.py:112-121)
        if self._compaction_threshold is not None:
            self._compact()
        super()._prepare_for_merge_state()

    # -------------------------------------------- cache-counter maintenance
    # every path that rewrites the raw cache must keep _cached_samples true,
    # or merge-fed accumulators would never compact (unbounded growth) and
    # reset metrics would compact spuriously
    def _recount_cache(self) -> None:
        self._cached_samples = sum(int(a.shape[0]) for a in self.inputs)
        if (
            self._compaction_threshold is not None
            and self._cached_samples >= self._compaction_threshold
        ):
            self._compact()

    def merge_state(self, metrics):
        super().merge_state(metrics)
        self._recount_cache()
        return self

    def reset(self):
        super().reset()
        self._cached_samples = 0
        return self

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        super().load_state_dict(state_dict, strict)
        self._recount_cache()


class BinaryAUROC(_BinaryCurveMetric):
    """Streaming area under the ROC curve (exact, sort-based).

    By default state is the full sample cache (reference design,
    ``auroc.py:55-71``); with ``compaction_threshold`` set, state is a
    bounded exact unique-threshold summary. For fixed-size approximate state
    use the binned PRC metrics instead.
    """

    def compute(self) -> jax.Array:
        counts = self._all_counts()
        if counts is None:
            return jnp.asarray(0.5)
        return binary_auroc_counts_kernel(*counts)


class BinaryAUPRC(_BinaryCurveMetric):
    """Streaming area under the PR curve (average precision).

    Framework extension (not in the reference snapshot v0.0.3; required by
    BASELINE.md config 2)."""

    def compute(self) -> jax.Array:
        counts = self._all_counts()
        if counts is None:
            return jnp.asarray(0.0)
        return binary_auprc_counts_kernel(*counts)

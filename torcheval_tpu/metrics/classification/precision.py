"""Precision class metrics.

Reference: ``torcheval/metrics/classification/precision.py``.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.precision import (
    _binary_precision_update,
    _precision_compute,
    _precision_input_check,
    _precision_param_check,
    _precision_update,
    _warn_nan_classes,
)
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


def _prec_fold(input, target, num_classes, average):
    num_tp, num_fp, num_label = _precision_update(
        input, target, num_classes, average
    )
    return {"num_tp": num_tp, "num_fp": num_fp, "num_label": num_label}


def _binprec_fold(input, target, threshold):
    num_tp, num_fp, num_label = _binary_precision_update(
        input, target, threshold
    )
    return {"num_tp": num_tp, "num_fp": num_fp, "num_label": num_label}


class MulticlassPrecision(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming multiclass precision.

    Reference parity: ``classification/precision.py:25-160``. State triple
    (num_tp, num_fp, num_label).
    """

    _fold_fn = staticmethod(_prec_fold)
    # pure terminal compute inside the window-step program; the NaN-class
    # warning is host-side and hooks the result (_on_window_result)
    _compute_fn = staticmethod(_precision_compute)

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _precision_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        shape = () if average == "micro" else (num_classes,)
        for name in ("num_tp", "num_fp", "num_label"):
            self._add_state(
                name, zeros_state(shape, dtype=jnp.int32), reduction=Reduction.SUM
            )
        self._init_deferred()
        self._fold_params = (self.num_classes, self.average)
        self._compute_params = (self.average,)

    def _update_check(self, input, target) -> None:
        _precision_input_check(input, target, self.num_classes)

    def update(self, input, target) -> "MulticlassPrecision":
        self._defer(self._input(input), self._input(target))
        return self

    def _on_window_result(self, result):
        if self.average in (None, "None"):
            _warn_nan_classes(self.num_tp, self.num_fp, "Precision")
        return result

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["MulticlassPrecision"]) -> "MulticlassPrecision":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.num_tp = self.num_tp + jax.device_put(metric.num_tp, self.device)
            self.num_fp = self.num_fp + jax.device_put(metric.num_fp, self.device)
            self.num_label = self.num_label + jax.device_put(
                metric.num_label, self.device
            )
        return self


class BinaryPrecision(MulticlassPrecision):
    """Streaming binary precision with thresholding.

    Reference parity: ``classification/precision.py:163-214``.
    """

    _fold_fn = staticmethod(_binprec_fold)


    def __init__(
        self, *, threshold: float = 0.5, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.threshold = threshold
        self._fold_params = (threshold,)

    def _update_check(self, input, target) -> None:
        if input.shape != target.shape:
            raise ValueError(
                "The `input` and `target` should have the same dimensions, "
                f"got shapes {input.shape} and {target.shape}."
            )
        if target.ndim != 1:
            raise ValueError(
                f"target should be a one-dimensional tensor, got shape {target.shape}."
            )

    def update(self, input, target) -> "BinaryPrecision":
        self._defer(self._input(input), self._input(target))
        return self

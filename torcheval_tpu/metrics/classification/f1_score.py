"""F1 class metrics.

Reference: ``torcheval/metrics/classification/f1_score.py``.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.f1_score import (
    _binary_f1_score_update,
    _f1_input_check,
    _f1_score_compute,
    _f1_score_param_check,
    _f1_score_update,
    _warn_empty_classes,
)
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


def _f1_fold(input, target, num_classes, average):
    num_tp, num_label, num_prediction = _f1_score_update(
        input, target, num_classes, average
    )
    return {
        "num_tp": num_tp,
        "num_label": num_label,
        "num_prediction": num_prediction,
    }


def _binf1_fold(input, target, threshold):
    num_tp, num_label, num_prediction = _binary_f1_score_update(
        input, target, threshold
    )
    return {
        "num_tp": num_tp,
        "num_label": num_label,
        "num_prediction": num_prediction,
    }


class MulticlassF1Score(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming multiclass F1.

    Reference parity: ``classification/f1_score.py:26-155``. State triple
    (num_tp, num_label, num_prediction), scalar (micro) or per-class.
    """

    _fold_fn = staticmethod(_f1_fold)
    # pure terminal compute: rides inside the window-step program at
    # compute() time (metrics/deferred.py); the empty-class warning is
    # host-side and hooks the result instead (_on_window_result)
    _compute_fn = staticmethod(_f1_score_compute)

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _f1_score_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        shape = () if average == "micro" else (num_classes,)
        for name in ("num_tp", "num_label", "num_prediction"):
            self._add_state(
                name, zeros_state(shape, dtype=jnp.int32), reduction=Reduction.SUM
            )
        self._init_deferred()
        self._fold_params = (self.num_classes, self.average)
        self._compute_params = (self.average,)

    def _update_check(self, input, target) -> None:
        _f1_input_check(input, target, self.num_classes, "multiclass f1 score")

    def update(self, input, target) -> "MulticlassF1Score":
        self._defer(self._input(input), self._input(target))
        return self

    def _on_window_result(self, result):
        if self.average != "micro":
            _warn_empty_classes(self.num_label)  # async, post-fold state
        return result

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["MulticlassF1Score"]) -> "MulticlassF1Score":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.num_tp = self.num_tp + jax.device_put(metric.num_tp, self.device)
            self.num_label = self.num_label + jax.device_put(
                metric.num_label, self.device
            )
            self.num_prediction = self.num_prediction + jax.device_put(
                metric.num_prediction, self.device
            )
        return self


class BinaryF1Score(MulticlassF1Score):
    """Streaming binary F1 with thresholding.

    Reference parity: ``classification/f1_score.py:158-218``.
    """

    _fold_fn = staticmethod(_binf1_fold)


    def __init__(
        self, *, threshold: float = 0.5, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.threshold = threshold
        self._fold_params = (threshold,)

    def _update_check(self, input, target) -> None:
        if input.ndim != 1 or target.ndim != 1 or input.shape != target.shape:
            raise ValueError(
                "input and target should be one-dimensional tensors of the same "
                f"shape, got {input.shape} and {target.shape}."
            )

    def update(self, input, target) -> "BinaryF1Score":
        self._defer(self._input(input), self._input(target))
        return self

"""Recall class metrics.

Reference: ``torcheval/metrics/classification/recall.py``.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.recall import (
    _binary_recall_compute,
    _binary_recall_update,
    _recall_compute,
    _recall_input_check,
    _recall_param_check,
    _recall_update,
    _warn_nan_recall,
)
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


def _rec_fold(input, target, num_classes, average):
    num_tp, num_labels, num_predictions = _recall_update(
        input, target, num_classes, average
    )
    return {
        "num_tp": num_tp,
        "num_labels": num_labels,
        "num_predictions": num_predictions,
    }


def _binrec_fold(input, target, threshold):
    num_tp, num_true_labels = _binary_recall_update(input, target, threshold)
    return {"num_tp": num_tp, "num_true_labels": num_true_labels}


class MulticlassRecall(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming multiclass recall.

    Reference parity: ``classification/recall.py:103-245``. State triple
    (num_tp, num_labels, num_predictions).
    """

    _fold_fn = staticmethod(_rec_fold)
    # pure terminal compute inside the window-step program; the NaN-recall
    # warning is host-side and hooks the result (_on_window_result)
    _compute_fn = staticmethod(_recall_compute)

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _recall_param_check(num_classes, average)
        self.num_classes = num_classes
        self.average = average
        shape = () if average == "micro" else (num_classes,)
        for name in ("num_tp", "num_labels", "num_predictions"):
            self._add_state(
                name, zeros_state(shape, dtype=jnp.int32), reduction=Reduction.SUM
            )
        self._init_deferred()
        self._fold_params = (self.num_classes, self.average)
        self._compute_params = (self.average,)

    def _update_check(self, input, target) -> None:
        _recall_input_check(input, target, self.num_classes)

    def update(self, input, target) -> "MulticlassRecall":
        self._defer(self._input(input), self._input(target))
        return self

    def _on_window_result(self, result):
        if self.average != "micro":
            _warn_nan_recall(self.num_labels)  # async, post-fold state
        return result

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["MulticlassRecall"]) -> "MulticlassRecall":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.num_tp = self.num_tp + jax.device_put(metric.num_tp, self.device)
            self.num_labels = self.num_labels + jax.device_put(
                metric.num_labels, self.device
            )
            self.num_predictions = self.num_predictions + jax.device_put(
                metric.num_predictions, self.device
            )
        return self


class BinaryRecall(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming binary recall with thresholding.

    Reference parity: ``classification/recall.py:26-100``. State pair
    (num_tp, num_true_labels).
    """

    _fold_fn = staticmethod(_binrec_fold)
    _compute_fn = staticmethod(_binary_recall_compute)

    def __init__(
        self, *, threshold: float = 0.5, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.threshold = threshold
        self._add_state("num_tp", zeros_state((), dtype=jnp.int32), reduction=Reduction.SUM)
        self._add_state(
            "num_true_labels", zeros_state((), dtype=jnp.int32), reduction=Reduction.SUM
        )
        self._init_deferred()
        self._fold_params = (threshold,)

    def _update_check(self, input, target) -> None:
        if input.shape != target.shape:
            raise ValueError(
                "The `input` and `target` should have the same dimensions, "
                f"got shapes {input.shape} and {target.shape}."
            )
        if target.ndim != 1:
            raise ValueError(
                f"target should be a one-dimensional tensor, got shape {target.shape}."
            )

    def update(self, input, target) -> "BinaryRecall":
        self._defer(self._input(input), self._input(target))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["BinaryRecall"]) -> "BinaryRecall":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.num_tp = self.num_tp + jax.device_put(metric.num_tp, self.device)
            self.num_true_labels = self.num_true_labels + jax.device_put(
                metric.num_true_labels, self.device
            )
        return self

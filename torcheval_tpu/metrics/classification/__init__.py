from torcheval_tpu.metrics.classification.accuracy import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.metrics.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
)
from torcheval_tpu.metrics.classification.f1_score import (
    BinaryF1Score,
    MulticlassF1Score,
)
from torcheval_tpu.metrics.classification.precision import (
    BinaryPrecision,
    MulticlassPrecision,
)
from torcheval_tpu.metrics.classification.recall import BinaryRecall, MulticlassRecall

__all__ = [
    "BinaryAccuracy",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryPrecision",
    "BinaryRecall",
    "MulticlassAccuracy",
    "MulticlassConfusionMatrix",
    "MulticlassF1Score",
    "MulticlassPrecision",
    "MulticlassRecall",
    "MultilabelAccuracy",
    "TopKMultilabelAccuracy",
]

"""Accuracy class metrics.

Reference: ``torcheval/metrics/classification/accuracy.py`` — thin streaming
accumulators over the pure kernels in
``torcheval_tpu.metrics.functional.classification.accuracy``.

Updates are **deferred** (``metrics/deferred.py``): each ``update()`` is an
O(1) host append, and the counting kernel runs over the concatenated pending
batches in one fused dispatch at read time or on a memory budget — the TPU
replacement for the reference's per-batch eager scatter
(``accuracy.py:271-273``).
"""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.accuracy import (
    _accuracy_compute,
    _accuracy_param_check,
    _accuracy_update_input_check,
    _binary_accuracy_update,
    _multiclass_accuracy_update,
    _multilabel_accuracy_param_check,
    _multilabel_accuracy_update,
    _multilabel_shape_check,
    _topk_multilabel_accuracy_param_check,
    _topk_multilabel_accuracy_update,
)
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike

TAccuracy = TypeVar("TAccuracy", bound="MulticlassAccuracy")


# module-level fold functions: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py)
def _acc_fold(input, target, average, num_classes, k):
    num_correct, num_total = _multiclass_accuracy_update(
        input, target, average, num_classes, k
    )
    return {"num_correct": num_correct, "num_total": num_total}


def _binacc_fold(input, target, threshold):
    num_correct, num_total = _binary_accuracy_update(input, target, threshold)
    return {"num_correct": num_correct, "num_total": num_total}


def _mlacc_fold(input, target, threshold, criteria):
    num_correct, num_total = _multilabel_accuracy_update(
        input, target, threshold, criteria
    )
    return {"num_correct": num_correct, "num_total": num_total}


def _topk_fold(input, target, criteria, k, topk_method):
    num_correct, num_total = _topk_multilabel_accuracy_update(
        input, target, criteria, k, topk_method
    )
    return {"num_correct": num_correct, "num_total": num_total}


class MulticlassAccuracy(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming multiclass accuracy.

    Reference parity: ``classification/accuracy.py:32-144``. State is a
    scalar pair (micro) or per-class ``(num_classes,)`` int32 counters.
    """

    _fold_fn = staticmethod(_acc_fold)
    _fold_per_chunk = True
    # pure terminal compute: rides inside the window-step program at
    # compute() time (metrics/deferred.py), zero extra dispatches
    _compute_fn = staticmethod(_accuracy_compute)

    def __init__(
        self,
        *,
        average: Optional[str] = "micro",
        num_classes: Optional[int] = None,
        k: int = 1,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _accuracy_param_check(average, num_classes, k)
        self.average = average
        self.num_classes = num_classes
        self.k = k
        shape = () if average == "micro" else (num_classes,)
        self._add_state(
            "num_correct", zeros_state(shape, dtype=jnp.int32), reduction=Reduction.SUM
        )
        self._add_state(
            "num_total", zeros_state(shape, dtype=jnp.int32), reduction=Reduction.SUM
        )
        self._init_deferred()
        self._fold_params = (self.average, self.num_classes, self.k)
        self._compute_params = (self.average,)

    def _update_check(self, input, target) -> None:
        # shape-only: memoised per batch signature by the _defer fast path
        _accuracy_update_input_check(input, target, self.num_classes, self.k)

    def update(self, input, target) -> "MulticlassAccuracy":
        self._defer(self._input(input), self._input(target))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["MulticlassAccuracy"]) -> "MulticlassAccuracy":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.num_correct = self.num_correct + jax.device_put(
                metric.num_correct, self.device
            )
            self.num_total = self.num_total + jax.device_put(
                metric.num_total, self.device
            )
        return self


class BinaryAccuracy(MulticlassAccuracy):
    """Streaming binary accuracy with thresholding.

    Reference parity: ``classification/accuracy.py:147-204``.
    """

    _fold_fn = staticmethod(_binacc_fold)

    def __init__(
        self, *, threshold: float = 0.5, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.threshold = threshold
        self._fold_params = (threshold,)

    def _update_check(self, input, target) -> None:
        _multilabel_shape_check(input, target)
        if target.ndim != 1:
            raise ValueError(
                f"target should be a one-dimensional tensor, got shape {target.shape}."
            )

    def update(self, input, target) -> "BinaryAccuracy":
        self._defer(self._input(input), self._input(target))
        return self


class MultilabelAccuracy(MulticlassAccuracy):
    """Streaming multilabel accuracy under a configurable criterion.

    Reference parity: ``classification/accuracy.py:207-302``.
    """

    _fold_fn = staticmethod(_mlacc_fold)

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        criteria: str = "exact_match",
        device: DeviceLike = None,
    ) -> None:
        _multilabel_accuracy_param_check(criteria)
        super().__init__(device=device)
        self.threshold = threshold
        self.criteria = criteria
        self._fold_params = (threshold, criteria)

    def _update_check(self, input, target) -> None:
        _multilabel_shape_check(input, target)

    def update(self, input, target) -> "MultilabelAccuracy":
        self._defer(self._input(input), self._input(target))
        return self


class TopKMultilabelAccuracy(MulticlassAccuracy):
    """Streaming multilabel accuracy where predictions are the top-k scores.

    Reference parity: ``classification/accuracy.py:305-394``, with the
    hardcoded ``topk(k=2)`` bug (``functional/.../accuracy.py:394``) fixed.

    Deferral note (ISSUE 2 satellite): updates ride the DeferredFoldMixin
    append path like every counter metric, and the ``lax.top_k`` stats core
    (``_topk_multilabel_stats``) runs inside the shared fold program — one
    dispatch per budget window, scan-based when the batch shape is steady.
    At BASELINE config 4's sizes ((8192, 10000) float32 scores ≈ 328 MB per
    batch) each chunk alone exceeds ``_DEFER_BUDGET_BYTES``, so the memory
    valve legitimately folds once per update there; the leg is bounded by
    the top-k kernel plus one dispatch floor per 328 MB batch, not by host
    eagerness (see bench.py::config4_topk_multilabel).

    The top-k kernel inside the fold is the streaming selection engine
    (``ops/topk.py``): at L=10k the ``auto`` pick streams label tiles
    through VMEM (Pallas, TPU) or the threshold-prune two-stage sort (XLA
    backends) instead of ``lax.top_k``'s full-width sort. ``topk_method``
    forces one lowering — the bench's interleaved A/B legs pin
    ``"dense"`` (the pre-engine baseline) against ``"auto"``.
    """

    _fold_fn = staticmethod(_topk_fold)
    # the streaming top-k engine's sharded Pallas lowering rides
    # custom_partitioning, which has no jax.vmap batching rule — multi-chunk
    # stacked folds keep the sequential lax.scan body instead
    _fold_vmap = False

    def __init__(
        self,
        *,
        criteria: str = "exact_match",
        k: int = 2,
        topk_method: str = "auto",
        device: DeviceLike = None,
    ) -> None:
        _topk_multilabel_accuracy_param_check(criteria, k)
        # validate the engine method EAGERLY, like criteria/k above: updates
        # defer, so a typo here would otherwise only surface at compute() —
        # after the whole eval stream has been accepted
        from torcheval_tpu.ops.topk import _METHODS

        if topk_method not in _METHODS:
            raise ValueError(
                f"topk_method must be one of {_METHODS}, got {topk_method!r}."
            )
        super().__init__(device=device)
        self.criteria = criteria
        self.k = k
        self.topk_method = topk_method
        self._fold_params = (criteria, k, topk_method)

    def _update_check(self, input, target) -> None:
        _multilabel_shape_check(input, target)
        if input.ndim != 2:
            raise ValueError(
                "input should have shape (num_sample, num_classes) for k > 1, "
                f"got shape {input.shape}."
            )

    def update(self, input, target) -> "TopKMultilabelAccuracy":
        self._defer(self._input(input), self._input(target))
        return self

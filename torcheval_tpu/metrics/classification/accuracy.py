"""Accuracy class metrics.

Reference: ``torcheval/metrics/classification/accuracy.py`` — thin streaming
accumulators over the pure kernels in
``torcheval_tpu.metrics.functional.classification.accuracy``.
"""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.classification.accuracy import (
    _accuracy_compute,
    _accuracy_param_check,
    _accuracy_update_input_check,
    _binary_accuracy_update,
    _multiclass_accuracy_update,
    _multilabel_accuracy_param_check,
    _multilabel_accuracy_update,
    _multilabel_shape_check,
    _topk_multilabel_accuracy_param_check,
    _topk_multilabel_accuracy_update,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike

TAccuracy = TypeVar("TAccuracy", bound="MulticlassAccuracy")


class MulticlassAccuracy(Metric[jax.Array]):
    """Streaming multiclass accuracy.

    Reference parity: ``classification/accuracy.py:32-144``. State is a
    scalar pair (micro) or per-class ``(num_classes,)`` int32 counters.
    """

    def __init__(
        self,
        *,
        average: Optional[str] = "micro",
        num_classes: Optional[int] = None,
        k: int = 1,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _accuracy_param_check(average, num_classes, k)
        self.average = average
        self.num_classes = num_classes
        self.k = k
        shape = () if average == "micro" else (num_classes,)
        self._add_state(
            "num_correct", jnp.zeros(shape, dtype=jnp.int32), reduction=Reduction.SUM
        )
        self._add_state(
            "num_total", jnp.zeros(shape, dtype=jnp.int32), reduction=Reduction.SUM
        )

    def update(self, input, target) -> "MulticlassAccuracy":
        input, target = self._input(input), self._input(target)
        _accuracy_update_input_check(input, target, self.num_classes, self.k)
        num_correct, num_total = _multiclass_accuracy_update(
            input, target, self.average, self.num_classes, self.k
        )
        self.num_correct = self.num_correct + num_correct
        self.num_total = self.num_total + num_total
        return self

    def compute(self) -> jax.Array:
        return _accuracy_compute(self.num_correct, self.num_total, self.average)

    def merge_state(self, metrics: Iterable["MulticlassAccuracy"]) -> "MulticlassAccuracy":
        for metric in metrics:
            self.num_correct = self.num_correct + jax.device_put(
                metric.num_correct, self.device
            )
            self.num_total = self.num_total + jax.device_put(
                metric.num_total, self.device
            )
        return self


class BinaryAccuracy(MulticlassAccuracy):
    """Streaming binary accuracy with thresholding.

    Reference parity: ``classification/accuracy.py:147-204``.
    """

    def __init__(
        self, *, threshold: float = 0.5, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.threshold = threshold

    def update(self, input, target) -> "BinaryAccuracy":
        input, target = self._input(input), self._input(target)
        _multilabel_shape_check(input, target)
        if target.ndim != 1:
            raise ValueError(
                f"target should be a one-dimensional tensor, got shape {target.shape}."
            )
        num_correct, num_total = _binary_accuracy_update(input, target, self.threshold)
        self.num_correct = self.num_correct + num_correct
        self.num_total = self.num_total + num_total
        return self


class MultilabelAccuracy(MulticlassAccuracy):
    """Streaming multilabel accuracy under a configurable criterion.

    Reference parity: ``classification/accuracy.py:207-302``.
    """

    def __init__(
        self,
        *,
        threshold: float = 0.5,
        criteria: str = "exact_match",
        device: DeviceLike = None,
    ) -> None:
        _multilabel_accuracy_param_check(criteria)
        super().__init__(device=device)
        self.threshold = threshold
        self.criteria = criteria

    def update(self, input, target) -> "MultilabelAccuracy":
        input, target = self._input(input), self._input(target)
        num_correct, num_total = _multilabel_accuracy_update(
            input, target, self.threshold, self.criteria
        )
        self.num_correct = self.num_correct + num_correct
        self.num_total = self.num_total + num_total
        return self


class TopKMultilabelAccuracy(MulticlassAccuracy):
    """Streaming multilabel accuracy where predictions are the top-k scores.

    Reference parity: ``classification/accuracy.py:305-394``, with the
    hardcoded ``topk(k=2)`` bug (``functional/.../accuracy.py:394``) fixed.
    """

    def __init__(
        self,
        *,
        criteria: str = "exact_match",
        k: int = 2,
        device: DeviceLike = None,
    ) -> None:
        _topk_multilabel_accuracy_param_check(criteria, k)
        super().__init__(device=device)
        self.criteria = criteria
        self.k = k

    def update(self, input, target) -> "TopKMultilabelAccuracy":
        input, target = self._input(input), self._input(target)
        num_correct, num_total = _topk_multilabel_accuracy_update(
            input, target, self.criteria, self.k
        )
        self.num_correct = self.num_correct + num_correct
        self.num_total = self.num_total + num_total
        return self

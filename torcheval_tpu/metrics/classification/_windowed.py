"""Shared machinery for windowed metrics (bounded per-update deque state).

One place for the window invariants so CTR and calibration (and future
windowed metrics) cannot drift: registration, the empty-window
representation, the stack/sum split, merge ordering, and the
config-compatibility contract for merges.
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.state import Reduction


class WindowedStateMixin:
    """Bounded per-update window over ``(2, num_tasks)`` stat rows.

    Host class contract: set ``num_tasks``, ``window_size`` and
    ``enable_lifetime`` attributes (validated here via ``_init_window``),
    list its lifetime state names in ``_LIFETIME_STATES``, and call
    ``_push_window(row_a, row_b)`` from ``update``.
    """

    _LIFETIME_STATES: Tuple[str, ...] = ()

    def _init_window(self, window_size: int) -> None:
        if window_size < 1:
            raise ValueError(
                "`window_size` value should be greater than and equal to 1, "
                f"but received {window_size}."
            )
        self.window_size = window_size
        # WINDOW, not CAT: cross-process sync must preserve per-update
        # window-entry boundaries (a CAT concat would merge every remote
        # update into a single window slot). The WINDOW lane ships each
        # rank's deque as ONE stacked (k, 2, num_tasks) array on the typed
        # two-round wire and re-imposes the deque bound at install — the
        # same bounded-window semantics as a local merge, without the
        # pickled object-gather this state rode until round 5.
        self._add_state(
            "window", deque(maxlen=window_size), reduction=Reduction.WINDOW
        )

    @property
    def _sync_schema_extra(self) -> Tuple:
        """Folded into the sync schema digest (``toolkit._schema_digest_row``)
        so ranks whose replicas disagree on the window configuration fail
        loudly and uniformly at the exchange — the eager ValueError
        ``_merge_windowed`` raises locally, transplanted to the typed wire
        (which folds without ever calling ``merge_state``)."""
        return (self.num_tasks, self.window_size, self.enable_lifetime)

    def _push_window(self, a: jax.Array, b: jax.Array) -> None:
        self.window.append(jnp.stack([a, b]))

    def _window_totals(self) -> Tuple[jax.Array, jax.Array]:
        if not self.window:
            zeros = jnp.zeros((self.num_tasks,), jnp.float32)
            return zeros, zeros
        stacked = jnp.sum(jnp.stack(list(self.window)), axis=0)
        return stacked[0], stacked[1]

    def _merge_windowed(self, metrics: Iterable) -> None:
        """Fold other replicas: lifetime states by sum, windows by extending
        this one's deque (others' entries appended in iteration order — the
        bounded window keeps the most recent ``window_size``). Replicas must
        agree on the window configuration; a mismatch would silently drop
        lifetime counters or miscount the bound. ALL replicas are validated
        before ANY folds so a mismatch raises with ``self`` unmutated (a
        mid-loop raise would leave a half-merged state)."""
        metrics = list(metrics)
        for metric in metrics:
            for attr in ("num_tasks", "window_size", "enable_lifetime"):
                if getattr(self, attr) != getattr(metric, attr):
                    raise ValueError(
                        f"Cannot merge {type(self).__name__} replicas with "
                        f"different `{attr}` ({getattr(self, attr)} vs "
                        f"{getattr(metric, attr)})."
                    )
        for metric in metrics:
            if self.enable_lifetime:
                for name in self._LIFETIME_STATES:
                    setattr(
                        self,
                        name,
                        getattr(self, name)
                        + jax.device_put(getattr(metric, name), self.device),
                    )
            self.window.extend(
                jax.device_put(row, self.device) for row in metric.window
            )

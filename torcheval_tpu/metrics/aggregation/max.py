"""Max metric. Reference: ``torcheval/metrics/aggregation/max.py``.

Updates are **deferred** (``metrics/deferred.py``). The running maximum is
not additive, so the fold threads state through ``jnp.maximum``
(``_fold_reduce``) instead of the default add — same one-dispatch-per-window
pipeline as every other deferred metric.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py)
def _max_deferred_fold(input):
    return {"max": jnp.max(input)}


def _max_deferred_compute(max):
    return max


class Max(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming maximum over all seen elements.

    Reference parity: ``aggregation/max.py:20-63``.
    """

    _fold_fn = staticmethod(_max_deferred_fold)
    _fold_per_chunk = True
    _fold_reduce = staticmethod(jnp.maximum)
    _compute_fn = staticmethod(_max_deferred_compute)  # identity: state IS the result

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("max", jnp.asarray(-jnp.inf), reduction=Reduction.MAX)
        self._init_deferred()

    def update(self, input: jax.Array) -> "Max":
        self._defer(self._input(input))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["Max"]) -> "Max":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.max = jnp.maximum(self.max, jax.device_put(metric.max, self.device))
        return self

"""Max metric. Reference: ``torcheval/metrics/aggregation/max.py``."""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike


class Max(Metric[jax.Array]):
    """Streaming maximum over all seen elements.

    Reference parity: ``aggregation/max.py:20-63``.
    """

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("max", jnp.asarray(-jnp.inf), reduction=Reduction.MAX)

    def update(self, input: jax.Array) -> "Max":
        input = self._input(input)
        self.max = jnp.maximum(self.max, jnp.max(input))
        return self

    def compute(self) -> jax.Array:
        return self.max

    def merge_state(self, metrics: Iterable["Max"]) -> "Max":
        for metric in metrics:
            self.max = jnp.maximum(self.max, jax.device_put(metric.max, self.device))
        return self

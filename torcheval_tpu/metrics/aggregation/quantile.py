"""Quantile metric — streaming quantiles on bounded memory (ISSUE 13).

A DDSketch-style relative-error quantile aggregation over the
``torcheval_tpu.sketch`` float-prefix buckets: state is ONE fixed-size
int32 bucket-count array (plus a NaN lane), folded by a pure additive
kernel — so updates defer through the window-step like every aggregation
metric (zero per-batch dispatch), ``merge_state``/sync are exact bucket
adds, and checkpoints are plain arrays. ``compute()`` returns, per
requested ``q``, the representative of the bucket holding the order
statistic of rank ``ceil(q * n)`` — within
``sketch.relative_error(bucket_bits)`` RELATIVE error of the true order
statistic for any score distribution, ties and heavy tails included
(rank resolution is exact: counts are integers).

No reference counterpart (torcheval has no quantile metric); the API
shape follows the aggregation family.
"""

from __future__ import annotations

import math
from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.sketch import DEFAULT_BUCKET_BITS, check_bucket_bits
from torcheval_tpu.sketch.histogram import (
    quantiles_from_counts,
    value_hist_fold,
)
from torcheval_tpu.utils.devices import DeviceLike


# module-level pure fold/compute (shared identity keys the deferred-fold
# jit cache across instances, metrics/deferred.py)
def _quantile_fold(input, bucket_bits):
    counts, nan = value_hist_fold(input, bucket_bits)
    return {"bucket_counts": counts, "nan_dropped": nan}


def _quantile_compute(bucket_counts, nan_dropped, q, bucket_bits):
    values = quantiles_from_counts(bucket_counts, q, bucket_bits)
    return values[0] if len(q) == 1 else values


class Quantile(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming quantile estimates over every element seen.

    Args:
        q: quantile(s) in ``[0, 1]`` — a float returns a scalar, a sequence
            returns one value per entry.
        bucket_count: sketch size (power of two). Memory is 4 bytes per
            bucket forever; the per-value relative error is
            ``sketch.relative_error(log2(bucket_count))``.
        nan_policy: ``"error"`` (default) raises at ``compute()`` if any
            NaN reached the fold (NaN has no order); ``"ignore"`` drops
            NaN elements silently (still counted in the state).

    An empty metric computes NaN (quantiles of nothing are undefined).
    """

    _fold_fn = staticmethod(_quantile_fold)
    _fold_per_chunk = True
    _compute_fn = staticmethod(_quantile_compute)
    # the serve per-tenant approx knob (sketch/cache.py::enable_metric_approx)
    # treats this metric as already-satisfied: its state IS a sketch
    _always_approx = True

    def __init__(
        self,
        q: Union[float, Iterable[float]] = 0.5,
        *,
        bucket_count: int = 1 << DEFAULT_BUCKET_BITS,
        nan_policy: str = "error",
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        qs = (float(q),) if isinstance(q, (int, float)) else tuple(
            float(x) for x in q
        )
        if not qs or any(
            not (0.0 <= x <= 1.0) or math.isnan(x) for x in qs
        ):
            raise ValueError(
                f"q must be (a sequence of) floats in [0, 1], got {q!r}."
            )
        if nan_policy not in ("error", "ignore"):
            raise ValueError(
                f'nan_policy must be "error" or "ignore", got {nan_policy!r}.'
            )
        bits = int(bucket_count).bit_length() - 1
        if bucket_count <= 0 or (1 << bits) != int(bucket_count):
            raise ValueError(
                f"bucket_count must be a power of two, got {bucket_count}."
            )
        check_bucket_bits(bits)
        self.q = qs
        self.nan_policy = nan_policy
        self._bucket_bits = bits
        self._add_state(
            "bucket_counts",
            zeros_state((1 << bits,), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )
        self._add_state(
            "nan_dropped",
            zeros_state((), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )
        self._init_deferred()
        self._fold_params = (bits,)
        self._compute_params = (qs, bits)

    # fold-relevant configuration: sync must reject replicas whose sketches
    # cannot bucket-add (different bucket_count) or whose computed quantiles
    # differ (different q)
    @property
    def _sync_schema_extra(self):
        return (self._bucket_bits, self.q)

    def update(self, input) -> "Quantile":
        self._defer(self._input(input))
        return self

    def compute(self) -> jax.Array:
        result = self._deferred_compute()
        from torcheval_tpu.sketch.cache import raise_sketch_overflow
        from torcheval_tpu.sketch.histogram import counts_exactness_flag

        # the int32-exact edge fails closed (one tiny jit + scalar read);
        # past ~2.1e9 total samples the rank cumsums would silently wrap
        raise_sketch_overflow(counts_exactness_flag(self.bucket_counts))
        if self.nan_policy == "error":
            dropped = int(self.nan_dropped)
            if dropped:
                raise ValueError(
                    f"{dropped} NaN value(s) reached the quantile sketch; "
                    "NaN has no order. Filter NaNs before update() or pass "
                    'nan_policy="ignore".'
                )
        return result

    def merge_state(self, metrics: Iterable["Quantile"]) -> "Quantile":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.bucket_counts = self.bucket_counts + jax.device_put(
                metric.bucket_counts, self.device
            )
            self.nan_dropped = self.nan_dropped + jax.device_put(
                metric.nan_dropped, self.device
            )
        return self

"""Cat metric: concatenate all seen inputs. Reference:
``torcheval/metrics/aggregation/cat.py``."""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike


class Cat(SampleCacheMetric[jax.Array]):
    """Concatenate all input arrays along ``dim``.

    Reference parity: ``aggregation/cat.py:24-96``, including the quirk that
    merging concatenates each source metric's cache along *that metric's*
    ``dim`` before appending.
    """

    def __init__(self, *, dim: int = 0, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self.dim = dim
        # Reduction.CAT means axis-0 all_gather concat; for dim != 0 the sync
        # layer must fall back to merge_state, so declare CUSTOM there.
        if dim == 0:
            self._add_cache_state("inputs")
        else:
            self._add_state("inputs", [], reduction=Reduction.CUSTOM)

    def update(self, input: jax.Array) -> "Cat":
        self.inputs.append(self._input(input))
        return self

    def compute(self) -> jax.Array:
        if not self.inputs:
            return jnp.empty((0,))
        return jnp.concatenate(self.inputs, axis=self.dim)

    def merge_state(self, metrics: Iterable["Cat"]) -> "Cat":
        for metric in metrics:
            if metric.inputs:
                self.inputs.append(
                    jax.device_put(
                        jnp.concatenate(metric.inputs, axis=metric.dim), self.device
                    )
                )
        return self

    def _prepare_for_merge_state(self) -> None:
        if self.inputs:
            self.inputs = [jnp.concatenate(self.inputs, axis=self.dim)]

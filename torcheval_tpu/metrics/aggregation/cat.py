"""Cat metric: concatenate all seen inputs. Reference:
``torcheval/metrics/aggregation/cat.py``."""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike


class Cat(Metric[jax.Array]):
    """Concatenate all input arrays along ``dim``.

    Sample-cache metric: state is a Python list of device arrays (appends are
    O(1) host ops; no device work until :meth:`compute`).
    Reference parity: ``aggregation/cat.py:24-96``, including the quirk that
    merging concatenates each source metric's cache along *that metric's*
    ``dim`` before appending.
    """

    def __init__(self, *, dim: int = 0, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self.dim = dim
        # Reduction.CAT means axis-0 all_gather concat; for dim != 0 the sync
        # layer must fall back to merge_state, so declare CUSTOM there.
        self._add_state(
            "inputs", [], reduction=Reduction.CAT if dim == 0 else Reduction.CUSTOM
        )

    def update(self, input: jax.Array) -> "Cat":
        self.inputs.append(self._input(input))
        return self

    def compute(self) -> jax.Array:
        if not self.inputs:
            return jnp.empty((0,))
        return jnp.concatenate(self.inputs, axis=self.dim)

    def merge_state(self, metrics: Iterable["Cat"]) -> "Cat":
        for metric in metrics:
            if metric.inputs:
                self.inputs.append(
                    jax.device_put(
                        jnp.concatenate(metric.inputs, axis=metric.dim), self.device
                    )
                )
        return self

    def _prepare_for_merge_state(self) -> None:
        if self.inputs:
            self.inputs = [jnp.concatenate(self.inputs, axis=self.dim)]

"""Cat metric: concatenate all seen inputs. Reference:
``torcheval/metrics/aggregation/cat.py``.

ISSUE 13 / ROADMAP 1(c): ``approx=`` swaps the unbounded concat cache for a
resident value sketch — the score-cache histogram mode that lets CAT-shaped
state ride the quantized sync codecs at O(buckets) wire bytes.
``compute()`` then returns the weighted-histogram view ``(values, counts)``
over the NONEMPTY buckets (bucket representatives + their multiplicities —
the approximate multiset of everything seen, each value within
``sketch.relative_error(bucket_bits)``). Requires ``dim == 0`` (the sketch
pools elements; higher-dim concat structure is not representable).
"""

from __future__ import annotations

from typing import Iterable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.sketch import (
    DEFAULT_BUCKET_BITS,
    ValueSketchCacheMixin,
    bucket_representatives,
    resolve_approx,
)
from torcheval_tpu.utils.devices import DeviceLike


class Cat(ValueSketchCacheMixin, SampleCacheMetric[jax.Array]):
    """Concatenate all input arrays along ``dim``.

    Reference parity: ``aggregation/cat.py:24-96``, including the quirk that
    merging concatenates each source metric's cache along *that metric's*
    ``dim`` before appending. With ``approx=`` set, state is a bounded value
    sketch instead (module docstring).
    """

    def __init__(
        self, *, dim: int = 0, approx=None, device: DeviceLike = None
    ) -> None:
        super().__init__(device=device)
        self.dim = dim
        bits = resolve_approx(approx, default_bits=DEFAULT_BUCKET_BITS)
        if bits is not None and dim != 0:
            if approx is None:
                # env-driven opt-in cannot apply here: stay exact, loudly,
                # rather than raise inside code that never mentioned approx
                # (the MulticlassPrecisionRecallCurve convention)
                from torcheval_tpu.utils.telemetry import log_once

                log_once(
                    "cat_approx_needs_dim0",
                    "TORCHEVAL_TPU_APPROX is set but Cat(dim=%d) cannot "
                    "sketch (the sketch pools elements; higher-dimension "
                    "concat structure is not representable) — this metric "
                    "stays exact.",
                    dim,
                )
                bits = None
            else:
                raise ValueError(
                    "approx= requires dim=0: the sketch pools elements and "
                    "cannot represent higher-dimension concat structure."
                )
        # Reduction.CAT means axis-0 all_gather concat; for dim != 0 the sync
        # layer must fall back to merge_state, so declare CUSTOM there.
        if dim == 0:
            self._add_cache_state("inputs")
        else:
            self._add_state("inputs", [], reduction=Reduction.CUSTOM)
        if bits is not None:
            self._init_value_sketch(bits, "inputs")

    def update(self, input: jax.Array) -> "Cat":
        input = self._input(input)
        self.inputs.append(input)
        if self._sketch_enabled():
            self._sketch_stage(input)
        return self

    def compute(
        self,
    ) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        if self._sketch_enabled():
            counts, nan, overflow = self._sketch_counts_parts()
            from torcheval_tpu.sketch.cache import raise_sketch_overflow

            raise_sketch_overflow(overflow)
            self._sketch_check_nan(nan)
            c = np.asarray(counts)
            keep = c > 0
            reps = bucket_representatives(self._sketch_bits)[keep]
            return jnp.asarray(reps), jnp.asarray(c[keep])
        if not self.inputs:
            return jnp.empty((0,))
        return jnp.concatenate(self.inputs, axis=self.dim)

    def merge_state(self, metrics: Iterable["Cat"]) -> "Cat":
        metrics = list(metrics)
        for metric in metrics:
            if metric.inputs:
                self.inputs.append(
                    jax.device_put(
                        jnp.concatenate(metric.inputs, axis=metric.dim), self.device
                    )
                )
        if self._sketch_enabled():
            self._sketch_merge_from(metrics)
            self._sketch_recount()
        return self

    def _prepare_for_merge_state(self) -> None:
        if self._sketch_enabled():
            self._sketch_fold()
        if self.inputs:
            self.inputs = [jnp.concatenate(self.inputs, axis=self.dim)]

from torcheval_tpu.metrics.aggregation.cat import Cat
from torcheval_tpu.metrics.aggregation.max import Max
from torcheval_tpu.metrics.aggregation.mean import Mean
from torcheval_tpu.metrics.aggregation.min import Min
from torcheval_tpu.metrics.aggregation.quantile import Quantile
from torcheval_tpu.metrics.aggregation.sum import Sum
from torcheval_tpu.metrics.aggregation.throughput import Throughput

__all__ = ["Cat", "Max", "Mean", "Min", "Quantile", "Sum", "Throughput"]

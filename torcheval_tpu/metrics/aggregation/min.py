"""Min metric. Reference: ``torcheval/metrics/aggregation/min.py``."""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike


class Min(Metric[jax.Array]):
    """Streaming minimum over all seen elements.

    Reference parity: ``aggregation/min.py:20-63``.
    """

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("min", jnp.asarray(jnp.inf), reduction=Reduction.MIN)

    def update(self, input: jax.Array) -> "Min":
        input = self._input(input)
        self.min = jnp.minimum(self.min, jnp.min(input))
        return self

    def compute(self) -> jax.Array:
        return self.min

    def merge_state(self, metrics: Iterable["Min"]) -> "Min":
        for metric in metrics:
            self.min = jnp.minimum(self.min, jax.device_put(metric.min, self.device))
        return self

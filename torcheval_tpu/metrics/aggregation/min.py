"""Min metric. Reference: ``torcheval/metrics/aggregation/min.py``.

Updates are **deferred** (``metrics/deferred.py``); the fold threads state
through ``jnp.minimum`` (``_fold_reduce``) — see :mod:`.max`.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.devices import DeviceLike


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py)
def _min_deferred_fold(input):
    return {"min": jnp.min(input)}


def _min_deferred_compute(min):
    return min


class Min(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming minimum over all seen elements.

    Reference parity: ``aggregation/min.py:20-63``.
    """

    _fold_fn = staticmethod(_min_deferred_fold)
    _fold_per_chunk = True
    _fold_reduce = staticmethod(jnp.minimum)
    _compute_fn = staticmethod(_min_deferred_compute)  # identity: state IS the result

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("min", jnp.asarray(jnp.inf), reduction=Reduction.MIN)
        self._init_deferred()

    def update(self, input: jax.Array) -> "Min":
        self._defer(self._input(input))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["Min"]) -> "Min":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.min = jnp.minimum(self.min, jax.device_put(metric.min, self.device))
        return self

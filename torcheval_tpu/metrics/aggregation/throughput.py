"""Throughput metric. Reference: ``torcheval/metrics/aggregation/throughput.py``.

The only metric whose ``update`` takes host scalars, so it stays off the jit
path entirely (SURVEY §7 "host-scalar metrics"): state is kept as jnp scalars
for checkpoint/sync uniformity, but updates are trivial host-side adds.
"""

from __future__ import annotations

import logging
from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike
from torcheval_tpu.utils.numerics import safe_div
from torcheval_tpu.utils.tracing import async_value_warn

_logger = logging.getLogger(__name__)


class Throughput(Metric[jax.Array]):
    """Items processed per second.

    Distributed merge sums counts but takes the **max** elapsed time across
    replicas — in a synchronous program the slowest rank gates overall
    throughput (reference: ``aggregation/throughput.py:97-108``).
    """

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("num_total", zeros_state(), reduction=Reduction.SUM)
        self._add_state("elapsed_time_sec", zeros_state(), reduction=Reduction.MAX)

    def update(self, num_processed: int, elapsed_time_sec: float) -> "Throughput":
        if num_processed < 0:
            raise ValueError(
                f"Expected num_processed to be a non-negative number, but received {num_processed}."
            )
        if elapsed_time_sec <= 0:
            raise ValueError(
                f"Expected elapsed_time_sec to be a positive number, but received {elapsed_time_sec}."
            )
        self.num_total = self.num_total + num_processed
        self.elapsed_time_sec = self.elapsed_time_sec + elapsed_time_sec
        return self

    def compute(self) -> jax.Array:
        # trace-safe + async warning, branch-free result, as in Mean.compute
        def _check(t) -> None:
            if t == 0.0:
                _logger.warning(
                    "No calls to update() have been made - returning 0.0"
                )

        async_value_warn(_check, self.elapsed_time_sec)
        return safe_div(self.num_total, self.elapsed_time_sec)

    def merge_state(self, metrics: Iterable["Throughput"]) -> "Throughput":
        for metric in metrics:
            self.num_total = self.num_total + jax.device_put(
                metric.num_total, self.device
            )
            self.elapsed_time_sec = jnp.maximum(
                self.elapsed_time_sec,
                jax.device_put(metric.elapsed_time_sec, self.device),
            )
        return self

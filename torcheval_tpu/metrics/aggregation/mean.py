"""Mean metric. Reference: ``torcheval/metrics/aggregation/mean.py``.

Updates are **deferred** (``metrics/deferred.py``); see :mod:`.sum` for the
default-weight single-column chunk convention this module shares.
"""

from __future__ import annotations

import logging
from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.aggregation.mean import _mean_update
from torcheval_tpu.metrics.functional.aggregation.sum import _weight_check
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike
from torcheval_tpu.utils.numerics import safe_div
from torcheval_tpu.utils.tracing import async_value_warn

_logger = logging.getLogger(__name__)


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py). A non-default weight
# defers as a second chunk column; arity discriminates.
def _mean_deferred_fold(input, weight=None):
    if weight is None:
        return {
            "weighted_sum": jnp.sum(input),
            "weights": jnp.asarray(float(input.size), dtype=jnp.float32),
        }
    weighted_sum, total_weight = _mean_update(input, weight)
    return {"weighted_sum": weighted_sum, "weights": total_weight}


def _mean_deferred_compute(weighted_sum, weights):
    return safe_div(weighted_sum, weights)


class Mean(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming weighted mean: ``sum(weight * input) / sum(weight)``.

    Reference parity: ``aggregation/mean.py:20-102``, with one documented fix:
    the reference treats an exactly-zero ``weighted_sum`` as "no updates yet"
    (``mean.py:92-94``), returning 0.0 for legitimately zero-mean data. We test
    ``weights == 0`` instead, which is the correct no-update signal.
    """

    _fold_fn = staticmethod(_mean_deferred_fold)
    _fold_per_chunk = True
    # pure terminal compute riding the window step; the no-update warning
    # is host-side and hooks the result (_on_window_result)
    _compute_fn = staticmethod(_mean_deferred_compute)

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("weighted_sum", zeros_state(), reduction=Reduction.SUM)
        self._add_state("weights", zeros_state(), reduction=Reduction.SUM)
        self._init_deferred()

    def update(
        self,
        input: jax.Array,
        *,
        weight: Union[float, int, jax.Array] = 1.0,
    ) -> "Mean":
        input = self._input(input)
        if isinstance(weight, (int, float)) and weight == 1.0:
            # default weight: nothing to validate; single-column chunk
            # (see module doc)
            self._defer(input)
        else:
            self._defer(input, _weight_check(input, weight))
        return self

    def _on_window_result(self, result):
        # trace-safe + async: the no-update warning reads the value back on a
        # daemon thread (utils/tracing.py) so compute never blocks on the
        # device stream; it reads the POST-FOLD state attribute, so it holds
        # whether the compute ran eagerly or inside the window-step program
        def _check(w) -> None:
            if w == 0.0:
                _logger.warning(
                    "No calls to update() have been made - returning 0.0"
                )

        async_value_warn(_check, self.weights)
        return result

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["Mean"]) -> "Mean":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.weighted_sum = self.weighted_sum + jax.device_put(
                metric.weighted_sum, self.device
            )
            self.weights = self.weights + jax.device_put(metric.weights, self.device)
        return self

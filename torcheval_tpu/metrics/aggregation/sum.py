"""Sum metric. Reference: ``torcheval/metrics/aggregation/sum.py``.

Updates are **deferred** (``metrics/deferred.py``): ``update()`` is an O(1)
host append and the reduction folds over the pending stream in one fused
dispatch. The default-weight path defers only the input column, so inside a
``MetricCollection`` the pending chunks stay identical across members and
the whole collection folds in one program.
"""

from __future__ import annotations

from typing import Iterable, Union

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.aggregation.sum import _sum_update, _weight_check
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py). A non-default weight
# defers as a second chunk column; arity discriminates.
def _sum_deferred_fold(input, weight=None):
    if weight is None:
        return {"weighted_sum": jnp.sum(input)}
    return {"weighted_sum": _sum_update(input, weight)}


def _sum_deferred_compute(weighted_sum):
    return weighted_sum


class Sum(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming (weighted) sum.

    Reference parity: ``aggregation/sum.py:20-86``.
    """

    _fold_fn = staticmethod(_sum_deferred_fold)
    _fold_per_chunk = True
    # identity terminal compute: inside the window step the folded state IS
    # the result, so compute() costs zero extra dispatches
    _compute_fn = staticmethod(_sum_deferred_compute)

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("weighted_sum", zeros_state(), reduction=Reduction.SUM)
        self._init_deferred()

    def update(
        self,
        input: jax.Array,
        *,
        weight: Union[float, int, jax.Array] = 1.0,
    ) -> "Sum":
        input = self._input(input)
        if isinstance(weight, (int, float)) and weight == 1.0:
            # default weight: nothing to validate, and the chunk stays a
            # single column so sibling metrics fed the same placed input
            # group-fold with it
            self._defer(input)
        else:
            self._defer(input, _weight_check(input, weight))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["Sum"]) -> "Sum":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.weighted_sum = self.weighted_sum + jax.device_put(
                metric.weighted_sum, self.device
            )
        return self

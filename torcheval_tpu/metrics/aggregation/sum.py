"""Sum metric. Reference: ``torcheval/metrics/aggregation/sum.py``."""

from __future__ import annotations

from typing import Iterable, Union

import jax

from torcheval_tpu.metrics.functional.aggregation.sum import _sum_update, _weight_check
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


class Sum(Metric[jax.Array]):
    """Streaming (weighted) sum.

    Reference parity: ``aggregation/sum.py:20-86``.
    """

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("weighted_sum", zeros_state(), reduction=Reduction.SUM)

    def update(
        self,
        input: jax.Array,
        *,
        weight: Union[float, int, jax.Array] = 1.0,
    ) -> "Sum":
        input = self._input(input)
        weight = _weight_check(input, weight)
        self.weighted_sum = self.weighted_sum + _sum_update(input, weight)
        return self

    def compute(self) -> jax.Array:
        return self.weighted_sum

    def merge_state(self, metrics: Iterable["Sum"]) -> "Sum":
        for metric in metrics:
            self.weighted_sum = self.weighted_sum + jax.device_put(
                metric.weighted_sum, self.device
            )
        return self

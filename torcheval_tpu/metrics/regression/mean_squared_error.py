"""MeanSquaredError metric. Reference:
``torcheval/metrics/regression/mean_squared_error.py``.

The reference's ``sum_squared_error`` starts scalar and is lazily promoted to
``(n_output,)`` on the first 2-D update (``mean_squared_error.py:80-84,
108-113``); here JAX broadcasting performs the same promotion for free —
``zeros(()) + vec`` yields ``vec``.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.regression.mean_squared_error import (
    _mean_squared_error_compute,
    _mean_squared_error_param_check,
    _mean_squared_error_update,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


class MeanSquaredError(Metric[jax.Array]):
    """Streaming mean squared error with optional per-sample weights.

    Args:
        multioutput: ``"uniform_average"`` (default) or ``"raw_values"``.

    Reference parity: ``regression/mean_squared_error.py:23-140``.
    """

    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _mean_squared_error_param_check(multioutput)
        self.multioutput = multioutput
        self._add_state("sum_squared_error", zeros_state(), reduction=Reduction.SUM)
        # int32 while updates are unweighted (exact counting to 2**31);
        # a weighted update promotes the accumulator to float32
        self._add_state(
            "sum_weight", zeros_state((), dtype=jnp.int32), reduction=Reduction.SUM
        )

    def update(
        self,
        input,
        target,
        *,
        sample_weight: Optional[jax.Array] = None,
    ) -> "MeanSquaredError":
        input = self._input(input)
        target = self._input(target)
        if sample_weight is not None:
            sample_weight = self._input(sample_weight)
        sse, sw = _mean_squared_error_update(input, target, sample_weight)
        self.sum_squared_error = self.sum_squared_error + sse
        self.sum_weight = self.sum_weight + sw
        return self

    def compute(self) -> jax.Array:
        return _mean_squared_error_compute(
            self.sum_squared_error, self.multioutput, self.sum_weight
        )

    def merge_state(
        self, metrics: Iterable["MeanSquaredError"]
    ) -> "MeanSquaredError":
        for metric in metrics:
            self.sum_squared_error = self.sum_squared_error + jax.device_put(
                metric.sum_squared_error, self.device
            )
            self.sum_weight = self.sum_weight + jax.device_put(
                metric.sum_weight, self.device
            )
        return self

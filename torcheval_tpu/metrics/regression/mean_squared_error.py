"""MeanSquaredError metric. Reference:
``torcheval/metrics/regression/mean_squared_error.py``.

The reference's ``sum_squared_error`` starts scalar and is lazily promoted to
``(n_output,)`` on the first 2-D update (``mean_squared_error.py:80-84,
108-113``); here JAX broadcasting performs the same promotion for free —
``zeros(()) + vec`` yields ``vec``.

Updates are **deferred** (``metrics/deferred.py``): each ``update()`` is an
O(1) host append, and the squared-error fold runs over the pending batch
stream in one fused dispatch at read time or on a memory budget — inside a
``MetricCollection`` it shares that one program with every other deferred
member.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.regression.mean_squared_error import (
    _mean_squared_error_compute,
    _mean_squared_error_param_check,
    _mean_squared_error_update_input_check,
    _mse_fold,
    _mse_fold_weighted,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py). The optional sample
# weight defers as a third chunk column; arity discriminates.
def _mse_deferred_fold(input, target, sample_weight=None):
    if sample_weight is None:
        sse, sw = _mse_fold(input, target)
    else:
        sse, sw = _mse_fold_weighted(input, target, sample_weight)
    return {"sum_squared_error": sse, "sum_weight": sw}


def _mse_deferred_compute(sum_squared_error, sum_weight, multioutput):
    """State-ordered adapter for the window-step terminal compute (the
    functional takes ``multioutput`` between the two states)."""
    return _mean_squared_error_compute(sum_squared_error, multioutput, sum_weight)


class MeanSquaredError(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming mean squared error with optional per-sample weights.

    Args:
        multioutput: ``"uniform_average"`` (default) or ``"raw_values"``.

    Reference parity: ``regression/mean_squared_error.py:23-140``.
    """

    _fold_fn = staticmethod(_mse_deferred_fold)
    _fold_per_chunk = True
    _compute_fn = staticmethod(_mse_deferred_compute)

    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _mean_squared_error_param_check(multioutput)
        self.multioutput = multioutput
        self._add_state("sum_squared_error", zeros_state(), reduction=Reduction.SUM)
        # int32 while updates are unweighted (exact counting to 2**31);
        # a weighted update promotes the accumulator to float32 at fold time
        self._add_state(
            "sum_weight", zeros_state((), dtype=jnp.int32), reduction=Reduction.SUM
        )
        self._init_deferred()
        self._compute_params = (multioutput,)

    def _update_check(self, input, target, sample_weight=None) -> None:
        _mean_squared_error_update_input_check(input, target, sample_weight)

    def update(
        self,
        input,
        target,
        *,
        sample_weight: Optional[jax.Array] = None,
    ) -> "MeanSquaredError":
        input = self._input(input)
        target = self._input(target)
        if sample_weight is None:
            self._defer(input, target)
        else:
            self._defer(input, target, self._input(sample_weight))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(
        self, metrics: Iterable["MeanSquaredError"]
    ) -> "MeanSquaredError":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.sum_squared_error = self.sum_squared_error + jax.device_put(
                metric.sum_squared_error, self.device
            )
            self.sum_weight = self.sum_weight + jax.device_put(
                metric.sum_weight, self.device
            )
        return self

"""R2Score metric. Reference: ``torcheval/metrics/regression/r2_score.py``.

Updates are **deferred** (``metrics/deferred.py``): the four sufficient
statistics fold over the pending batch stream in one fused dispatch at read
time or on a memory budget, shared with every other deferred member of a
``MetricCollection``.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.regression.r2_score import (
    _r2_fold,
    _r2_score_compute,
    _r2_score_param_check,
    _r2_score_update_input_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike

_STATE_NAMES = (
    "sum_squared_obs",
    "sum_obs",
    "sum_squared_residual",
    "num_obs",
)


# module-level fold function: shared identity keys the deferred-fold jit
# cache across metric instances (metrics/deferred.py)
def _r2_deferred_fold(input, target):
    return dict(zip(_STATE_NAMES, _r2_fold(input, target)))


class R2Score(DeferredFoldMixin, Metric[jax.Array]):
    """Streaming R-squared score over four sufficient statistics.

    Args:
        multioutput: ``"uniform_average"`` (default), ``"raw_values"``, or
            ``"variance_weighted"``.
        num_regressors: independent-variable count for adjusted R²
            (0 = standard R²).

    Reference parity: ``regression/r2_score.py:23-162``.
    """

    _fold_fn = staticmethod(_r2_deferred_fold)
    _fold_per_chunk = True

    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        num_regressors: int = 0,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _r2_score_param_check(multioutput, num_regressors)
        self.multioutput = multioutput
        self.num_regressors = num_regressors
        for name in _STATE_NAMES:
            # num_obs counts in int32 (exact to 2**31 samples)
            default = (
                zeros_state((), dtype=jnp.int32)
                if name == "num_obs"
                else zeros_state()
            )
            self._add_state(name, default, reduction=Reduction.SUM)
        self._init_deferred()

    def _update_check(self, input, target) -> None:
        _r2_score_update_input_check(input, target)

    def update(self, input, target) -> "R2Score":
        self._defer(self._input(input), self._input(target))
        return self

    # NOTE no _compute_fn: _r2_score_compute reads num_obs on the host
    # (insufficient-data errors) — it cannot ride inside the window-step
    # program, so compute() stays the eager fold-then-compute pair.
    def compute(self) -> jax.Array:
        self._fold_now()
        return _r2_score_compute(
            self.sum_squared_obs,
            self.sum_obs,
            self.sum_squared_residual,
            self.num_obs,
            self.multioutput,
            self.num_regressors,
        )

    def merge_state(self, metrics: Iterable["R2Score"]) -> "R2Score":
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            for name in _STATE_NAMES:
                setattr(
                    self,
                    name,
                    getattr(self, name)
                    + jax.device_put(getattr(metric, name), self.device),
                )
        return self

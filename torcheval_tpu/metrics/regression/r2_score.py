"""R2Score metric. Reference: ``torcheval/metrics/regression/r2_score.py``."""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.functional.regression.r2_score import (
    _r2_score_compute,
    _r2_score_param_check,
    _r2_score_update,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike

_STATE_NAMES = (
    "sum_squared_obs",
    "sum_obs",
    "sum_squared_residual",
    "num_obs",
)


class R2Score(Metric[jax.Array]):
    """Streaming R-squared score over four sufficient statistics.

    Args:
        multioutput: ``"uniform_average"`` (default), ``"raw_values"``, or
            ``"variance_weighted"``.
        num_regressors: independent-variable count for adjusted R²
            (0 = standard R²).

    Reference parity: ``regression/r2_score.py:23-162``.
    """

    def __init__(
        self,
        *,
        multioutput: str = "uniform_average",
        num_regressors: int = 0,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        _r2_score_param_check(multioutput, num_regressors)
        self.multioutput = multioutput
        self.num_regressors = num_regressors
        for name in _STATE_NAMES:
            # num_obs counts in int32 (exact to 2**31 samples)
            default = (
                zeros_state((), dtype=jnp.int32)
                if name == "num_obs"
                else zeros_state()
            )
            self._add_state(name, default, reduction=Reduction.SUM)

    def update(self, input, target) -> "R2Score":
        input = self._input(input)
        target = self._input(target)
        stats = _r2_score_update(input, target)
        for name, value in zip(_STATE_NAMES, stats):
            setattr(self, name, getattr(self, name) + value)
        return self

    def compute(self) -> jax.Array:
        return _r2_score_compute(
            self.sum_squared_obs,
            self.sum_obs,
            self.sum_squared_residual,
            self.num_obs,
            self.multioutput,
            self.num_regressors,
        )

    def merge_state(self, metrics: Iterable["R2Score"]) -> "R2Score":
        for metric in metrics:
            for name in _STATE_NAMES:
                setattr(
                    self,
                    name,
                    getattr(self, name)
                    + jax.device_put(getattr(metric, name), self.device),
                )
        return self

from torcheval_tpu.metrics.regression.mean_squared_error import MeanSquaredError
from torcheval_tpu.metrics.regression.r2_score import R2Score

__all__ = ["MeanSquaredError", "R2Score"]

"""Shared base for sample-cache metrics (list-of-arrays state).

The reference has four metrics whose state is an append-only cache of
per-batch arrays merged by axis-0 concat — ``Cat``, ``HitRate``,
``ReciprocalRank`` (``ranking/hit_rate.py:75-88``), ``BinaryAUROC`` and the
PRC family (``classification/auroc.py:69-94``). Each re-implements the same
append / concat-merge / compact-before-sync protocol. This base implements it
once: subclasses register caches with :meth:`_add_cache_state` and only write
``update`` / ``compute``.

Appends are O(1) host-list ops; no device work happens until ``compute`` (or
``_prepare_for_merge_state``, which compacts each cache to a single array so a
sync collective moves one buffer per state).
"""

from __future__ import annotations

from typing import Iterable, List, TypeVar

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction

TComputeReturn = TypeVar("TComputeReturn")
TSelf = TypeVar("TSelf", bound="SampleCacheMetric")


class SampleCacheMetric(Metric[TComputeReturn]):
    """Metric whose state variables are lists of arrays concatenated on axis 0."""

    def _add_cache_state(self, name: str, *, dtype=jnp.float32) -> None:
        """Register a CAT cache. ``dtype`` declares the cache's element type,
        which only matters on the empty-cache read path: an empty
        ``compute()`` must still return an array of the dtype the metric
        documents, not whatever ``jnp.empty`` defaults to."""
        if not hasattr(self, "_cache_dtypes"):
            self._cache_dtypes = {}
        self._cache_dtypes[name] = jnp.dtype(dtype)
        self._add_state(name, [], reduction=Reduction.CAT)

    def _cache_names(self) -> List[str]:
        return [
            name
            for name, default in self._state_name_to_default.items()
            if isinstance(default, list)
        ]

    def _concat_cache(self, name: str, *, empty_shape=(0,), empty_dtype=None) -> jax.Array:
        """Concatenate cache ``name`` (axis 0). An empty cache returns
        ``jnp.empty(empty_shape, empty_dtype)`` — ``empty_dtype`` defaults to
        the dtype declared at ``_add_cache_state`` time, so the empty read
        does not silently degrade to float32 for integer caches."""
        cache = getattr(self, name)
        if not cache:
            if empty_dtype is None:
                empty_dtype = getattr(self, "_cache_dtypes", {}).get(name)
            return jnp.empty(empty_shape, dtype=empty_dtype)
        return jnp.concatenate(cache, axis=0)

    def merge_state(self: TSelf, metrics: Iterable[TSelf]) -> TSelf:
        for metric in metrics:
            for name in self._cache_names():
                src = getattr(metric, name)
                if src:
                    getattr(self, name).append(
                        jax.device_put(jnp.concatenate(src, axis=0), self.device)
                    )
        return self

    def _prepare_for_merge_state(self) -> None:
        for name in self._cache_names():
            cache = getattr(self, name)
            if cache:
                setattr(self, name, [jnp.concatenate(cache, axis=0)])

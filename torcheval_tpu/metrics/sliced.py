"""``SlicedMetricCollection``: the same metrics across millions of cohorts.

ROADMAP item 3(a). A plain :class:`~torcheval_tpu.metrics.MetricCollection`
computes ONE global value per metric; real online eval wants that value per
user segment — per-cohort accuracy / AUROC / CTR over live traffic, at
thousands-to-millions of segments. The enabling observation is the PR 4
multiclass trick turned sideways: vmap the member's fold/compute math over
an extra axis and the per-slice marginal cost collapses to a vector lane
inside the ONE program the window already compiles.

Architecture
============

* **Dense slice axis.** Every member's state tree grows a LEADING
  ``[num_slices]`` dimension (``state[s]`` is slice ``s``'s state, exactly
  the standalone metric's shape past axis 0). Each batch arrives with a
  ``slice_ids`` integer column; the whole per-window fold + compute still
  compiles into ONE donated ``deferred.window_step`` program — the
  per-slice routing is an in-program ``segment_sum``/``segment_max`` over
  the dense row column, never a host-side per-slice loop or per-slice
  dispatch.
* **Sparse id → dense row mapping.** Cohort ids are arbitrary int64 under a
  power-law distribution; a :class:`SliceTable` interns them host-side in
  first-seen order (vectorized ``searchsorted`` lookup — O(N log R) per
  batch, no per-sample Python) and the program only ever sees dense int32
  rows. Dense capacity starts small and grows geometrically (a pure
  zero/default pad — interning is append-only, so existing rows never
  rehash), so a tenant whose id SPACE is huge but whose observed cohort set
  is small never pays rows it hasn't seen.
* **Generic member fold.** Any :class:`DeferredFoldMixin` metric whose fold
  is per-sample decomposable (every shipped counter/regression/aggregation
  fold) slices generically: the member's own ``_fold_fn`` is ``jax.vmap``-ed
  over the sample axis (batch-of-one calls), and the per-sample deltas
  scatter into the slice axis with the reduce-matched segment op. Counts
  are integer adds, so per-slice values are BIT-identical to running the
  standalone metric on each slice's samples alone.
* **Sketch members.** Curve metrics must be ``approx=`` (a per-slice exact
  sample cache would be O(samples) × slices); the sliced score sketch keeps
  O(buckets) per slice via a combined-index segment_sum
  (``sketch/cache.py::sliced_score_hist_fold``) — O(batch) scratch, not
  O(batch × buckets) — and may opt into coarser-than-standalone bucket
  widths (``curve_bucket_bits``) where a million cohorts make every bit of
  width hundreds of MB.
* **Sync rides unchanged by construction.** Sliced states are the same
  SUM/MAX/MIN lanes with a leading axis, so ``sync_and_compute`` moves
  every slice's state in the SAME two collective rounds regardless of
  slice count, and the quantized/bucket codecs (PRs 12–13) apply per lane
  as-is. Ragged per-rank cohort populations are reconciled AFTER the
  gather from data already on the wire: each member carries its id table
  as ``slice_ids_hi``/``slice_ids_lo`` int32 lanes (+ a ``slice_count``
  scalar), and :func:`align_sliced_gathered` remaps every rank's rows onto
  the sorted union table before the ordinary per-reduction fold — pure
  local work, zero extra collectives.

Layout contract (for the future per-window axis, ROADMAP 3(b))
==============================================================

The slice axis is ALWAYS the leading state axis and the fold routes it with
a dense int32 row column carried as the FIRST chunk column. A later
tumbling/sliding time-window axis must be added OUTSIDE the slice axis
(state ``[windows, slices, ...]``, windows rotating by leading-axis roll)
or as a second routing column folded into the combined segment index —
either composes with this module because nothing here assumes the slice
axis is axis -1, and the segment index construction
(``row * inner + sub``) nests. Compute vmaps over axis 0 only; a window
axis wraps it in one more ``jax.vmap``.

Results come back keyed by ORIGINAL ids: ``compute()`` returns
``{member: SlicedResult}`` where :class:`SlicedResult` is a plain dict
(``{"slice_ids": int64 ids, "values": per-slice values}`` — wire-
marshallable as-is) with convenience accessors.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as _P

from torcheval_tpu.metrics.collection import MetricCollection
from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.ops.scatter import segment_scatter
from torcheval_tpu.utils.devices import DeviceLike

__all__ = [
    "SliceTable",
    "SlicedResult",
    "SlicedMetricCollection",
    "check_sliceable",
    "align_sliced_gathered",
]

_DEFAULT_CAPACITY = 1024

_LO_MASK = np.int64(0xFFFFFFFF)


def _pack_ids(ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int64 ids → wire-safe int32 ``(hi, lo)`` halves. The ONE definition
    (with :func:`_unpack_ids`) of the split convention — the ``lo`` mask is
    what keeps negative ids exact through the round trip."""
    ids = np.asarray(ids, np.int64)
    return (ids >> 32).astype(np.int32), (ids & _LO_MASK).astype(np.int32)


def _unpack_ids(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (np.asarray(hi).astype(np.int64) << 32) | (
        np.asarray(lo).astype(np.int64) & _LO_MASK
    )


# ---------------------------------------------------------------- id table
class SliceTable:
    """Append-only intern table: original int64 slice ids → dense rows.

    Rows are assigned in first-seen order and NEVER move (growth is a pure
    capacity pad), which is what lets state grow by zero-padding and lets a
    checkpointed table round-trip bit-identically. Lookup is vectorized
    ``np.searchsorted`` over a sorted shadow index — O(N log R) per batch
    with no per-sample Python; the shadow only rebuilds on batches that
    actually registered new ids (rare once the hot cohort set is seen).
    """

    __slots__ = (
        "ids",
        "count",
        "capacity",
        "granularity",
        "version",
        "_sorted_ids",
        "_sorted_rows",
    )

    def __init__(
        self, capacity: int = _DEFAULT_CAPACITY, *, granularity: int = 1
    ) -> None:
        # >= 1 at construction; a capacity-0 table can still ARISE from the
        # sync union of all-empty ranks (replace()), and intern() grows it
        if not isinstance(capacity, int) or capacity < 1:
            raise ValueError(f"capacity must be an int >= 1, got {capacity!r}.")
        # dense capacity stays a multiple of ``granularity`` through every
        # growth path — the slice-axis sharding contract: each of N mesh
        # shards owns a contiguous block-range tile of capacity/N rows, so
        # the leading state axis must always divide evenly
        self.granularity = max(int(granularity), 1)
        self.capacity = self.round_capacity(capacity)
        self.count = 0
        self.ids = np.zeros(self.capacity, np.int64)
        self.version = 0  # bumped on every mutation: the id-state refresh key
        self._sorted_ids = np.empty(0, np.int64)
        self._sorted_rows = np.empty(0, np.int64)

    def round_capacity(self, capacity: int) -> int:
        """``capacity`` rounded up to the table's granularity (identity for
        the default granularity 1 — the unsharded layout is unchanged)."""
        g = self.granularity
        return -(-int(capacity) // g) * g

    def predict_growth(self, need: int) -> int:
        """The capacity :meth:`intern` would settle on for ``need`` rows —
        the ONE definition of the growth schedule (geometric doubling, then
        granularity round-up), shared with ``merge_collections``'s
        fail-closed pre-validation."""
        cap = max(self.capacity, 1)
        while cap < int(need):
            cap *= 2
        return self.round_capacity(cap)

    def _rebuild_index(self) -> None:
        order = np.argsort(self.ids[: self.count], kind="stable")
        self._sorted_ids = self.ids[: self.count][order]
        self._sorted_rows = order

    def _lookup(self, batch: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """``(rows, found_mask)`` for ``batch`` against the current table
        (rows are garbage where ``found`` is False)."""
        if self.count == 0:
            return np.zeros(batch.shape, np.int64), np.zeros(batch.shape, bool)
        pos = np.searchsorted(self._sorted_ids, batch)
        clip = np.minimum(pos, self._sorted_ids.shape[0] - 1)
        found = self._sorted_ids[clip] == batch
        return self._sorted_rows[clip], found

    def intern(self, slice_ids: Any) -> Tuple[np.ndarray, bool]:
        """Map a batch id column to dense int32 rows, registering unseen ids
        in first-seen order. Returns ``(rows, grew)`` — ``grew`` means the
        dense capacity changed and every member's state must pad to
        :attr:`capacity` BEFORE the rows are used."""
        batch = np.asarray(slice_ids)
        if batch.ndim != 1 or batch.dtype.kind not in "iu":
            raise ValueError(
                "slice_ids must be a 1-D integer column, got "
                f"shape {batch.shape} dtype {batch.dtype}."
            )
        batch = batch.astype(np.int64, copy=False)
        rows, found = self._lookup(batch)
        grew = False
        if not found.all():
            fresh_vals = batch[~found]
            uniq, first = np.unique(fresh_vals, return_index=True)
            fresh = uniq[np.argsort(first)]  # first-seen order, deterministic
            need = self.count + fresh.shape[0]
            if need > self.capacity:
                # max(..., 1) inside predict_growth: a zero-capacity table
                # exists after syncing all-empty ranks (union of nothing)
                # and must still grow
                new_cap = self.predict_growth(need)
                grown = np.zeros(new_cap, np.int64)
                grown[: self.count] = self.ids[: self.count]
                self.ids = grown
                self.capacity = new_cap
                grew = True
            self.ids[self.count : self.count + fresh.shape[0]] = fresh
            self.count += fresh.shape[0]
            self._rebuild_index()
            self.version += 1
            rows, found = self._lookup(batch)
            assert found.all()
        return rows.astype(np.int32), grew

    def mark(self) -> Tuple[int, int, np.ndarray]:
        """Rollback point for a transactional intern (review finding): the
        pre-intern ``(count, capacity, ids array)``. Growth allocates a
        FRESH ids array, so holding the old reference costs nothing and
        restores exactly."""
        return (self.count, self.capacity, self.ids)

    def rollback(self, mark: Tuple[int, int, np.ndarray]) -> None:
        """Undo everything since ``mark`` — registrations AND capacity
        growth. Used when growth is REJECTED (member states were never
        padded): without the rollback the table would stay grown while the
        members stayed small, and every later batch's ``grew=False`` would
        silently scatter new cohorts' samples out of the members' segment
        range."""
        self.count, self.capacity, self.ids = mark
        self._rebuild_index()
        self.version += 1

    def lookup_rows(self, slice_ids: np.ndarray) -> np.ndarray:
        """Rows for ids that MUST already be registered (merge remap)."""
        batch = np.asarray(slice_ids).astype(np.int64, copy=False)
        rows, found = self._lookup(batch)
        if not found.all():
            raise KeyError("lookup_rows() called with unregistered slice ids.")
        return rows.astype(np.int32)

    def registered_ids(self) -> np.ndarray:
        return self.ids[: self.count].copy()

    def replace(self, ids: np.ndarray, capacity: int) -> None:
        """Wholesale install (checkpoint restore / synced-union adoption).
        Idempotent: installing the content already held is a no-op beyond a
        version bump, so every member of a restored collection may replay
        the same install."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        if capacity < ids.shape[0]:
            raise ValueError(
                f"capacity {capacity} < registered id count {ids.shape[0]}."
            )
        if np.unique(ids).shape[0] != ids.shape[0]:
            raise ValueError("slice id table contains duplicate ids.")
        self.capacity = int(capacity)
        self.count = int(ids.shape[0])
        self.ids = np.zeros(self.capacity, np.int64)
        self.ids[: self.count] = ids
        self._rebuild_index()
        self.version += 1

    def clear(self) -> None:
        self.count = 0
        self._sorted_ids = np.empty(0, np.int64)
        self._sorted_rows = np.empty(0, np.int64)
        self.version += 1


# ----------------------------------------------------------------- results
class SlicedResult(dict):
    """Per-slice compute result keyed by ORIGINAL slice ids.

    A plain dict subclass (``{"slice_ids": np.int64[R], "values": tree of
    per-slice leaves}``) so it marshals through the serve wire's
    ``pack_tree`` and JSON-ish tooling unchanged — which is also why the
    sugar accessors must NOT shadow the dict protocol (``.values()`` stays
    the dict method; the per-slice leaves read as ``res["values"]`` or
    :attr:`slice_values`). ``values`` leaves carry the slice axis leading,
    aligned 1:1 with ``slice_ids``.
    """

    def __init__(self, slice_ids: np.ndarray, values: Any) -> None:
        super().__init__(
            slice_ids=np.asarray(slice_ids, np.int64), values=values
        )

    @property
    def slice_ids(self) -> np.ndarray:
        return self["slice_ids"]

    @property
    def slice_values(self) -> Any:
        return self["values"]

    @property
    def num_slices(self) -> int:
        return int(self["slice_ids"].shape[0])

    def value_of(self, slice_id: int) -> Any:
        idx = np.nonzero(self["slice_ids"] == int(slice_id))[0]
        if idx.size == 0:
            raise KeyError(f"slice id {slice_id!r} was never observed.")
        i = int(idx[0])
        return jax.tree_util.tree_map(lambda v: v[i], self["values"])

    def as_dict(self) -> Dict[int, Any]:
        # tree-aware like value_of (review finding): a tuple-valued member
        # compute must index each LEAF's slice axis, never the stack axis
        # np.asarray would invent over the tuple
        vals = jax.tree_util.tree_map(np.asarray, self["values"])
        return {
            int(i): jax.tree_util.tree_map(lambda v: v[n], vals)
            for n, i in enumerate(self["slice_ids"])
        }


# ----------------------------------------------------------- generic folds
_SEGMENT_OPS = {
    "sum": jax.ops.segment_sum,
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}
_REDUCE_KINDS = {None: "sum"}  # populated below (jnp identities)
_REDUCE_KINDS[jnp.maximum] = "max"
_REDUCE_KINDS[jnp.minimum] = "min"


def _sliced_fold(*xs):
    """Module-level sliced fold (one shared jit-cache identity for every
    generic member): the template's per-sample-decomposable ``_fold_fn``
    vmapped over the sample axis (batch-of-one calls keep the member math
    byte-for-byte the standalone kernel's), then ONE segment scatter into
    the dense slice axis. Trailing statics:
    ``(base_fn, base_params, num_slices, reduce_kind, shard)`` where
    ``shard`` is ``None`` or a hashable ``(mesh, axis)`` pair; leading
    operands: ``(rows, *update_columns)`` — concatenated whole-window
    columns (the concat fold regime: the segment op wants the full stream
    once). The scatter routes through ``ops.scatter.segment_scatter``:
    unsharded it resolves to the identical XLA segment op (or the Pallas
    VMEM kernel on TPU); sharded it applies each shard's block-range tile
    in-program with no state-sized collective."""
    base_fn, base_params, num_slices, reduce_kind, shard = xs[-5:]
    rows = xs[0].astype(jnp.int32)
    cols = xs[1:-5]
    per_sample = jax.vmap(
        lambda *a: base_fn(*(c[None] for c in a), *base_params)
    )(*cols)
    mesh, axis = shard if shard is not None else (None, None)
    # group same-(trailing-shape, dtype) deltas into ONE stacked segment op:
    # XLA:CPU's scatter is serial per update row, so the PASS count over the
    # batch — not the state count — is the cost; a binary counter pair folds
    # in one (N, 2) scatter instead of two (N,) scatters
    groups: Dict[Any, List[str]] = {}
    for name, delta in per_sample.items():
        groups.setdefault(
            (delta.shape[1:], jnp.result_type(delta)), []
        ).append(name)
    out = {}
    for (_shape, _dtype), names in groups.items():
        if len(names) == 1:
            name = names[0]
            out[name] = segment_scatter(
                per_sample[name],
                rows,
                num_slices,
                reduce=reduce_kind,
                mesh=mesh,
                axis=axis,
            )
            continue
        stacked = jnp.stack([per_sample[n] for n in names], axis=-1)
        folded = segment_scatter(
            stacked,
            rows,
            num_slices,
            reduce=reduce_kind,
            mesh=mesh,
            axis=axis,
        )
        for i, name in enumerate(names):
            out[name] = folded[..., i]
    return out


def _sliced_compute(*xs):
    """Module-level sliced terminal compute: the template's pure
    ``_compute_fn`` vmapped over the slice axis. Trailing statics:
    ``(base_fn, base_params, n_template_states)`` — the member's id lanes
    ride the registration order after the template states and are sliced
    off here."""
    base_fn, base_params, n_states = xs[-3:]
    states = xs[:n_states]
    return jax.vmap(lambda *s: base_fn(*s, *base_params))(*states)


# ------------------------------------------------------------ member shell
_ID_STATE_NAMES = ("slice_ids_hi", "slice_ids_lo", "slice_count")


class _SlicedMemberBase(DeferredFoldMixin, Metric):
    """Internal adapter: one template metric expanded over the slice axis.

    Rides the WHOLE existing deferred machinery — EvalWindow membership,
    the one-program donated window step, group folds, obs counters, the
    two-round sync wire, ``resilience.snapshot`` and serve evict/reattach —
    because it IS a ``DeferredFoldMixin`` metric whose states happen to
    carry a leading slice axis plus three id lanes:

    * template states, same names/dtypes/reductions, shape ``(cap, *S)``;
    * ``slice_ids_hi``/``slice_ids_lo`` int32 ``(cap,)`` — the int64 id
      table split into wire-safe 32-bit halves (jax's 32-bit default would
      silently truncate an int64 lane);
    * ``slice_count`` int32 scalar — registered-row watermark.

    The authoritative table is the host-side :class:`SliceTable` SHARED by
    every member of one collection; the id lanes are refreshed from it
    lazily on every state read (``state_dict`` / pre-sync), so the steady
    update loop never pays them.
    """

    _fold_per_chunk = False  # concat regime: one segment scatter per window
    _sliced_sync = True

    def __init__(
        self,
        table: SliceTable,
        device: DeviceLike = None,
        shard: Optional[Tuple[Mesh, str]] = None,
    ) -> None:
        if shard is not None:
            mesh, axis = shard
            if axis not in mesh.shape:
                raise ValueError(
                    f"mesh axis {axis!r} not in mesh axes "
                    f"{tuple(mesh.shape)}."
                )
            if device is None:
                # inputs and the replicated id lanes live mesh-wide; the
                # sliced states are RE-placed P(axis) after registration
                device = NamedSharding(mesh, _P())
        super().__init__(device=device)
        self._shard = shard
        self._shards = int(shard[0].shape[shard[1]]) if shard else 1
        self._table = table
        self._table_version = -1
        self._row_defaults: Dict[str, np.ndarray] = {}
        self._sliced_state_names: Tuple[str, ...] = ()

    # ------------------------------------------------------------- placement
    def _sliced_sharding(self) -> Optional[NamedSharding]:
        """The slice-axis state sharding: shard ``s`` of N owns the
        contiguous block-range tile ``[s*cap/N, (s+1)*cap/N)`` of the
        leading axis (``ops.topk.shard_tile_width`` decomposition). ``None``
        when unsharded."""
        if self._shard is None:
            return None
        mesh, axis = self._shard
        return NamedSharding(mesh, _P(axis))

    def _place_sliced_states(self) -> None:
        """(Re-)pin every sliced state's leading axis to the mesh tiles.
        Every path that materializes sliced state host-side or replicated —
        registration, growth, sync-union install, restore, merge, reset —
        funnels through here so the state is NEVER left replicated on a
        sharded member (the HLO-asserted no-replication bound)."""
        sharding = self._sliced_sharding()
        if sharding is None:
            return
        for name in self._sliced_state_names:
            setattr(
                self,
                name,
                jax.device_put(jnp.asarray(getattr(self, name)), sharding),
            )
        # id lanes + watermark stay replicated but must live on the SAME
        # mesh (one device set per donated window-step program)
        for name in _ID_STATE_NAMES:
            if hasattr(self, name):
                setattr(
                    self,
                    name,
                    jax.device_put(
                        jnp.asarray(getattr(self, name)), self._device
                    ),
                )

    def __deepcopy__(self, memo):
        # Mesh handles are process-local singletons (Device objects do not
        # pickle/deepcopy); share them by reference like Metric shares
        # _device — seeding the memo covers every nested reference too
        # (_shard, _fold_params)
        if self._shard is not None:
            memo[id(self._shard[0])] = self._shard[0]
            memo[id(self._shard)] = self._shard
        return super().__deepcopy__(memo)

    def __getstate__(self):
        # pickling degrades to UNSHARDED (matching Metric's Sharding
        # degradation): mesh handles cannot cross process boundaries; the
        # state payload is the global value either way
        state = super().__getstate__()
        if self._shard is not None:
            state["_shard"] = None
            state["_shards"] = 1
            state.pop("_fold_params", None)
            state.pop("_compute_params", None)
            for name in self._sliced_state_names + _ID_STATE_NAMES:
                if name in state:
                    state[name] = np.asarray(state[name])
        return state

    def __setstate__(self, state):
        refit = "_fold_params" not in state
        super().__setstate__(state)
        if refit:
            self._refit_params()

    # -------------------------------------------------------- registration
    def _register_sliced_state(
        self, name: str, row_default: np.ndarray, reduction: Reduction
    ) -> None:
        row_default = np.asarray(row_default)
        cap = self._table.capacity
        default = np.broadcast_to(
            row_default, (cap,) + row_default.shape
        ).copy()
        self._add_state(name, default, reduction=reduction)
        self._row_defaults[name] = row_default
        self._sliced_state_names = self._sliced_state_names + (name,)
        if self._shard is not None:
            sharding = self._sliced_sharding()
            setattr(
                self,
                name,
                jax.device_put(jnp.asarray(getattr(self, name)), sharding),
            )

    def _register_id_states(self) -> None:
        self._add_state(
            "slice_ids_hi",
            np.zeros(self._table.capacity, np.int32),
            reduction=Reduction.NONE,
        )
        self._add_state(
            "slice_ids_lo",
            np.zeros(self._table.capacity, np.int32),
            reduction=Reduction.NONE,
        )
        self._add_state(
            "slice_count", np.zeros((), np.int32), reduction=Reduction.NONE
        )
        # checkpoint-restore contract (resilience/snapshot.py): these states'
        # LEADING dim is the dense capacity and legitimately differs between
        # a fresh member and a grown checkpoint; trailing dims must match
        self._lead_resizable_states = frozenset(
            self._sliced_state_names + ("slice_ids_hi", "slice_ids_lo")
        )

    # ------------------------------------------------------------- re-size
    def _refit_params(self) -> None:
        """Subclass hook: rebuild ``_fold_params``/``_compute_params`` after
        the dense capacity changed (statics carry ``num_slices``)."""
        raise NotImplementedError

    def _check_capacity(self, capacity: int) -> None:
        """Subclass hook: raise if this member cannot represent ``capacity``
        dense rows — run for EVERY member BEFORE any member's state pads,
        so a failed growth never leaves the collection half-grown."""

    def _grow_to(self, capacity: int) -> None:
        """Pad every sliced state's leading axis to ``capacity`` (rows never
        move — interning is append-only, so growth is a pure default-pad;
        O(log total-slices) growth events under geometric doubling). On a
        sharded member growth runs host-side (the eager concat would have
        to reconcile a P(axis) operand with a replicated pad) and the grown
        state re-pins to the mesh tiles — rare by the doubling schedule, so
        the round trip never shows in the steady loop."""
        for name in self._sliced_state_names + ("slice_ids_hi", "slice_ids_lo"):
            cur = getattr(self, name)
            cur_len = int(cur.shape[0])
            if cur_len >= capacity:
                continue
            row_default = self._row_defaults.get(
                name, np.zeros((), np.int32)
            )
            if self._shard is not None:
                cur_np = np.asarray(cur)  # global gather of the tiles
                fill_np = np.broadcast_to(
                    np.asarray(row_default).astype(cur_np.dtype, copy=False),
                    (capacity - cur_len,) + tuple(np.shape(row_default)),
                )
                setattr(
                    self,
                    name,
                    np.concatenate([cur_np, fill_np], axis=0),
                )
            else:
                fill = jnp.broadcast_to(
                    jnp.asarray(row_default),
                    (capacity - cur_len,) + tuple(np.shape(row_default)),
                )
                setattr(
                    self,
                    name,
                    jnp.concatenate([jnp.asarray(cur), fill], axis=0),
                )
            self._state_name_to_default[name] = np.broadcast_to(
                np.asarray(row_default), (capacity,) + np.shape(row_default)
            ).copy()
        self._place_sliced_states()
        self._refit_params()

    # ------------------------------------------------------- id-lane sync
    def _refresh_id_states(self) -> None:
        """Mirror the host table into the registered id lanes (lazy: only
        when the table changed since the last refresh, so the steady update
        loop never touches them)."""
        t = self._table
        if (
            self._table_version == t.version
            and int(getattr(self, "slice_ids_hi").shape[0]) == t.capacity
        ):
            return
        ids = np.zeros(t.capacity, np.int64)
        ids[: t.count] = t.ids[: t.count]
        hi, lo = _pack_ids(ids)
        if self._shard is not None:
            # replicate onto the member's mesh: one device set per program
            self.slice_ids_hi = jax.device_put(hi, self._device)
            self.slice_ids_lo = jax.device_put(lo, self._device)
            self.slice_count = jax.device_put(np.int32(t.count), self._device)
        else:
            self.slice_ids_hi = jnp.asarray(hi)
            self.slice_ids_lo = jnp.asarray(lo)
            self.slice_count = jnp.asarray(np.int32(t.count))
        self._table_version = t.version

    def _adopt_state_shapes(self) -> None:
        """Re-derive table + capacity from the id LANES — the restore /
        synced-install direction (states are authoritative there, the host
        table is rebuilt to match). Shared by ``load_state_dict``, the
        sync-union install and serve reattach; idempotent across the
        members of one collection (they install identical content into the
        shared table)."""
        hi = np.asarray(self.slice_ids_hi)
        lo = np.asarray(self.slice_ids_lo)
        count = int(np.asarray(self.slice_count))
        capacity = int(hi.shape[0])
        padded = self._table.round_capacity(capacity)
        if padded != capacity:
            # sharded per-shard align: an installed union capacity (any
            # ragged per-rank cohort count) pads up to the shard multiple
            # so the leading axis keeps dividing into the block-range tiles
            pad = padded - capacity
            hi = np.concatenate([hi, np.zeros(pad, np.int32)])
            lo = np.concatenate([lo, np.zeros(pad, np.int32)])
            self.slice_ids_hi = hi
            self.slice_ids_lo = lo
            for name in self._sliced_state_names:
                arr = np.asarray(getattr(self, name))
                row_default = np.asarray(self._row_defaults[name])
                fill = np.broadcast_to(
                    row_default.astype(arr.dtype, copy=False),
                    (pad,) + arr.shape[1:],
                )
                setattr(self, name, np.concatenate([arr, fill], axis=0))
            capacity = padded
        ids = _unpack_ids(hi, lo)
        self._table.replace(ids[:count], capacity)
        for name in self._sliced_state_names:
            row_default = self._row_defaults[name]
            self._state_name_to_default[name] = np.broadcast_to(
                np.asarray(row_default), (capacity,) + np.shape(row_default)
            ).copy()
        self._state_name_to_default["slice_ids_hi"] = np.zeros(
            capacity, np.int32
        )
        self._state_name_to_default["slice_ids_lo"] = np.zeros(
            capacity, np.int32
        )
        self._place_sliced_states()
        self._table_version = self._table.version
        self._refit_params()

    # ----------------------------------------------------- protocol plumbing
    @property
    def _sync_schema_extra(self) -> Tuple:
        # capacity deliberately NOT here: ragged per-rank cohort populations
        # must still digest-match (alignment happens post-gather)
        return ("sliced",) + self._schema_extra_tail()

    def _schema_extra_tail(self) -> Tuple:
        return ()

    def state_dict(self):
        self._refresh_id_states()
        return super().state_dict()

    def _prepare_for_merge_state(self) -> None:
        super()._prepare_for_merge_state()
        self._refresh_id_states()

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        super().load_state_dict(state_dict, strict)
        self._adopt_state_shapes()

    def update(self, rows, *args):
        """Internal-contract update: ``rows`` is the DENSE int32 row column
        the owning collection interned (standalone callers must intern
        through the collection; raw cohort ids here would silently alias
        rows). Appends one chunk ``(rows, *args)``."""
        self._defer(self._input(rows), *(self._input(a) for a in args))
        return self

    def reset(self):
        out = super().reset()
        # default states land replicated via _device; re-pin the tiles
        self._place_sliced_states()
        return out

    def compute(self):
        return self._deferred_compute()

    def _wrap_values(self, values: Any) -> SlicedResult:
        count = self._table.count
        return SlicedResult(
            self._table.registered_ids(),
            jax.tree_util.tree_map(lambda v: v[:count], values),
        )

    def merge_state(self, metrics):
        """Merge other sliced replicas BY ORIGINAL ID: unseen ids append to
        this member's table (growing capacity as needed — the shared table
        grows once; sibling members pad on their own merge), then the
        other's rows scatter-combine into this member's rows. Bit-identical
        to having streamed the other's batches here (integer adds /
        extrema)."""
        metrics = list(metrics)
        self._fold_now()
        for other in metrics:
            other._fold_now()
        for other in metrics:
            o_count = other._table.count
            if o_count == 0:
                continue
            o_ids = other._table.registered_ids()
            mark = self._table.mark()
            rows_np, grew = self._table.intern(o_ids)
            if grew or self._table.capacity > int(
                getattr(self, self._sliced_state_names[0]).shape[0]
            ):
                # same fail-closed contract as _intern_and_grow: validate
                # the grown capacity BEFORE any state pads, and roll the
                # table back on rejection so the member stays consistent
                # (a _grow_to that failed mid-_refit_params would leave
                # padded states with stale fold params and a grown table)
                try:
                    self._check_capacity(self._table.capacity)
                except BaseException:
                    self._table.rollback(mark)
                    raise
                self._grow_to(self._table.capacity)
            rows = (
                jax.device_put(rows_np, self._device)
                if self._shard is not None
                else jnp.asarray(rows_np)
            )
            for name in self._sliced_state_names:
                # per-STATE declared reduction (review finding): a member
                # whose fold-reduce is sum can still carry MAX/MIN states
                # (config grids) — merging them additively would corrupt
                # exactly the rows both replicas hold
                red = self._state_name_to_reduction[name]
                mine = getattr(self, name)
                theirs = jax.device_put(
                    getattr(other, name)[:o_count], self.device
                )
                if red is Reduction.SUM:
                    merged = mine.at[rows].add(theirs)
                elif red is Reduction.MAX:
                    merged = mine.at[rows].max(theirs)
                else:  # Reduction.MIN (check_sliceable admits no others)
                    merged = mine.at[rows].min(theirs)
                setattr(self, name, merged)
        # the scatter-combine output's sharding follows GSPMD inference;
        # re-pin so merged state never lingers replicated on a sharded member
        self._place_sliced_states()
        return self


class _SlicedFoldMember(_SlicedMemberBase):
    """Generic slice expansion of one per-sample-decomposable deferred
    template (accuracy family, F1/precision/recall/confusion counts,
    MSE/NE sufficient statistics, Sum/Mean/Max/Min, CTR, calibration)."""

    _fold_fn = staticmethod(_sliced_fold)
    _compute_fn = staticmethod(_sliced_compute)

    def __init__(
        self,
        template: Metric,
        table: SliceTable,
        device: DeviceLike = None,
        shard: Optional[Tuple[Mesh, str]] = None,
    ) -> None:
        super().__init__(table, device=device, shard=shard)
        tcls = type(template)
        self._template_cls = tcls.__qualname__
        self._base_fold = tcls._fold_fn
        self._base_fold_params = tuple(template._fold_params)
        self._base_compute = tcls._compute_fn
        self._base_compute_params = tuple(template._compute_params)
        self._reduce_kind = _REDUCE_KINDS[tcls._fold_reduce]
        self._template_update_check = getattr(
            template, "_update_check", None
        )
        for name, red in template._state_name_to_reduction.items():
            self._register_sliced_state(
                name,
                np.asarray(template._state_name_to_default[name]),
                red,
            )
        self._register_id_states()
        self._init_deferred()
        self._refit_params()

    def _refit_params(self) -> None:
        self._fold_params = (
            self._base_fold,
            self._base_fold_params,
            self._table.capacity,
            self._reduce_kind,
            self._shard,
        )
        self._compute_params = (
            self._base_compute,
            self._base_compute_params,
            len(self._sliced_state_names),
        )

    def _schema_extra_tail(self) -> Tuple:
        return (self._template_cls,) + self._base_fold_params

    def _update_check(self, rows, *args) -> None:
        _check_rows_column(rows, args)
        check = self._template_update_check
        if check is not None:
            check(*args)

    def _on_window_result(self, result):
        return self._wrap_values(result)


# the three concrete reduce flavors: ``_fold_reduce`` must be a CLASS
# attribute (the deferred spec builders read ``type(m)._fold_reduce``)
class _SlicedFoldMemberSum(_SlicedFoldMember):
    _fold_reduce = None


class _SlicedFoldMemberMax(_SlicedFoldMember):
    _fold_reduce = staticmethod(jnp.maximum)


class _SlicedFoldMemberMin(_SlicedFoldMember):
    _fold_reduce = staticmethod(jnp.minimum)


_FOLD_MEMBER_BY_KIND = {
    "sum": _SlicedFoldMemberSum,
    "max": _SlicedFoldMemberMax,
    "min": _SlicedFoldMemberMin,
}


class _SlicedScoreSketchMember(_SlicedMemberBase):
    """Slice expansion of an ``approx=`` binary curve metric (BinaryAUROC /
    BinaryAUPRC): per-slice ``(B,)`` bucket histograms folded by ONE
    combined-index segment_sum, computed by the standalone sketch's own
    presorted counts kernel vmapped over the slice axis — per-slice values
    are bit-identical to the standalone ``approx=`` metric fed that slice's
    samples (same counts, same kernel)."""

    _fold_reduce = None
    _compute_fn = None  # bound below (module import order)

    def __init__(
        self,
        template: Metric,
        table: SliceTable,
        *,
        curve_bucket_bits: Optional[int] = None,
        device: DeviceLike = None,
        shard: Optional[Tuple[Mesh, str]] = None,
    ) -> None:
        from torcheval_tpu.sketch.cache import check_sliced_bucket_bits

        super().__init__(table, device=device, shard=shard)
        self._template_cls = type(template).__qualname__
        self._kind = (
            "auroc" if "AUROC" in self._template_cls else "auprc"
        )
        bits = (
            curve_bucket_bits
            if curve_bucket_bits is not None
            else template._sketch_bits
        )
        self._bits = check_sliced_bucket_bits(int(bits))
        # extent check BEFORE registering state: a capacity x width pair
        # past the int32 segment-index bound must reject instantly, not
        # after materializing multi-GB default histograms
        self._check_capacity(table.capacity)
        zero_hist = np.zeros((1 << self._bits,), np.int32)
        self._register_sliced_state("sketch_tp", zero_hist, Reduction.SUM)
        self._register_sliced_state("sketch_fp", zero_hist, Reduction.SUM)
        self._register_sliced_state(
            "sketch_nan_dropped", np.zeros((), np.int32), Reduction.SUM
        )
        self._register_id_states()
        self._init_deferred()
        self._refit_params()

    def _check_capacity(self, capacity: int) -> None:
        from torcheval_tpu.sketch.cache import check_sliced_sketch_extent

        # PER-SHARD bound: each shard's combined index runs over its own
        # capacity/shards tile, so sharding over N devices multiplies the
        # admissible cohort count by N — 100M+ cohorts is a capacity
        # statement, not an error
        check_sliced_sketch_extent(self._bits, capacity, shards=self._shards)

    def _refit_params(self) -> None:
        # fail closed BEFORE the int32 combined index can wrap (runs at
        # construction, every capacity growth, restore-adopt and sync-
        # union install, so the bound holds for the life of the member)
        self._check_capacity(self._table.capacity)
        self._fold_params = (self._bits, self._table.capacity, self._shard)
        self._compute_params = (self._bits, self._kind)

    def _schema_extra_tail(self) -> Tuple:
        return (self._template_cls, self._bits)

    def _update_check(self, rows, *args) -> None:
        _check_rows_column(rows, args)
        if len(args) != 2:
            raise ValueError(
                "sliced curve metrics take (slice_ids, scores, targets), "
                f"got {len(args)} update columns after the id column."
            )
        if args[0].shape != args[1].shape or args[0].ndim != 1:
            raise ValueError(
                "scores and targets must be matching 1-D columns, got "
                f"{args[0].shape} vs {args[1].shape}."
            )

    def _on_window_result(self, result):
        from torcheval_tpu.sketch.cache import (
            raise_sketch_nan,
            raise_sketch_overflow,
        )

        values, overflow, nan_total = result
        raise_sketch_overflow(overflow)
        raise_sketch_nan(nan_total, "sample(s)")
        return self._wrap_values(values)


def _bind_sketch_member_fns() -> None:
    # deferred import: sketch.cache must not import at this module's load
    # time from inside the metrics package __init__ chain
    from torcheval_tpu.sketch.cache import (
        sliced_curve_compute,
        sliced_score_hist_fold,
    )

    _SlicedScoreSketchMember._fold_fn = staticmethod(sliced_score_hist_fold)
    _SlicedScoreSketchMember._compute_fn = staticmethod(sliced_curve_compute)


_bind_sketch_member_fns()


def _check_rows_column(rows, args) -> None:
    if rows.ndim != 1 or rows.dtype not in (jnp.int32, np.int32):
        raise ValueError(
            "the slice row column must be 1-D int32 (the collection "
            f"interns ids before members see them), got shape {rows.shape} "
            f"dtype {rows.dtype}."
        )
    for a in args:
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] != rows.shape[0]:
            raise ValueError(
                "every update column must match the slice column's sample "
                f"count {rows.shape[0]}, got {a.shape}."
            )


# ------------------------------------------------------------- sliceability
def _is_sketch_curve(metric: Metric) -> bool:
    return hasattr(metric, "_compaction_threshold") and hasattr(
        metric, "_compact"
    )


def check_sliceable(metric: Metric, *, approx: Any = None) -> None:
    """Raise ``ValueError`` when ``metric`` cannot expand over a slice axis.

    Sliceable today: (a) any :class:`DeferredFoldMixin` metric whose fold
    is per-sample decomposable (``_fold_vmap`` true, a known reduce, a pure
    ``_compute_fn``, plain array states); (b) a FRESH binary ``approx=``
    curve metric (BinaryAUROC/AUPRC) — or one that WILL be switched by the
    serve per-tenant ``approx`` knob (``approx`` forwarded here so
    validate-then-commit covers slice expansion too, ISSUE 15 satellite).
    Everything else — sample-cache exact curves, host-state metrics,
    multiclass sketches — rejects with the reason."""
    if _is_sketch_curve(metric):
        if hasattr(metric, "num_classes"):
            raise ValueError(
                f"{type(metric).__qualname__} cannot be sliced: per-slice "
                "multiclass sketch state would be (slices, classes, "
                "buckets); slice the binary one-vs-all projections instead."
            )
        will_be_approx = metric._sketch_enabled() or (
            approx is not None and approx is not False
        )
        if not will_be_approx:
            raise ValueError(
                f"{type(metric).__qualname__} must run approx= to be "
                "sliced: a per-slice exact sample cache is O(samples) per "
                "slice and cannot survive the slice explosion."
            )
        if bool(getattr(metric, "inputs", None)) or bool(
            getattr(metric, "_cached_samples", 0)
        ):
            raise ValueError(
                "cannot slice a curve metric that already holds streamed "
                "samples; construct it fresh."
            )
        return
    if not isinstance(metric, DeferredFoldMixin):
        raise ValueError(
            f"{type(metric).__qualname__} cannot be sliced: only deferred "
            "array-state metrics (and approx= binary curves) expand over "
            "a slice axis."
        )
    cls = type(metric)
    if cls._compute_fn is None:
        raise ValueError(
            f"{cls.__qualname__} cannot be sliced: its compute has "
            "host-side behavior (no pure _compute_fn to vmap per slice)."
        )
    if not cls._fold_vmap:
        raise ValueError(
            f"{cls.__qualname__} cannot be sliced: its fold kernel has no "
            "vmap batching rule (custom_partitioning lowerings)."
        )
    if cls._fold_reduce not in _REDUCE_KINDS:
        raise ValueError(
            f"{cls.__qualname__} cannot be sliced: third-party "
            "_fold_reduce has no known per-slice segment op."
        )
    if getattr(metric, "_pending", None):
        raise ValueError(
            "cannot slice a metric that already holds streamed batches; "
            "construct it fresh."
        )
    for name, default in metric._state_name_to_default.items():
        if not hasattr(default, "shape"):
            raise ValueError(
                f"{cls.__qualname__} cannot be sliced: state {name!r} is "
                "not a plain array."
            )
        red = metric._state_name_to_reduction[name]
        if red not in (Reduction.SUM, Reduction.MAX, Reduction.MIN):
            raise ValueError(
                f"{cls.__qualname__} cannot be sliced: state {name!r} "
                f"declares Reduction.{red.name}, which has no leading-axis "
                "slice semantics."
            )


def _build_member(
    template: Metric,
    table: SliceTable,
    *,
    curve_bucket_bits: Optional[int] = None,
    shard: Optional[Tuple[Mesh, str]] = None,
) -> _SlicedMemberBase:
    check_sliceable(template)
    if _is_sketch_curve(template):
        return _SlicedScoreSketchMember(
            template, table, curve_bucket_bits=curve_bucket_bits, shard=shard
        )
    kind = _REDUCE_KINDS[type(template)._fold_reduce]
    return _FOLD_MEMBER_BY_KIND[kind](template, table, shard=shard)


# --------------------------------------------------------------- collection
class SlicedMetricCollection(MetricCollection):
    """Drive one metric set across many cohorts with one shared program.

    Example::

        col = SlicedMetricCollection({
            "acc": BinaryAccuracy(),
            "auroc": BinaryAUROC(approx=1024),
        }, capacity=4096)
        for slice_ids, scores, labels in stream:     # ids: any int64 cohorts
            col.update(slice_ids, scores, labels)
        results = col.compute()
        results["acc"].slice_ids, results["acc"].values   # aligned 1:1

    ``metrics`` values are TEMPLATES: each is expanded into an internal
    slice-axis member (the templates themselves are left untouched).
    ``capacity`` seeds the dense row capacity (grows geometrically);
    ``curve_bucket_bits`` optionally re-buckets sketch members coarser than
    the standalone floor (see ``sketch/cache.py::SLICED_MIN_BUCKET_BITS``).

    ``mesh_axis`` (optionally with an explicit ``mesh``) shards the leading
    slice axis of every member state across that named mesh axis: shard
    ``s`` of N owns the contiguous block-range row tile
    ``[s*cap/N, (s+1)*cap/N)``, the fold applies each shard's deltas
    in-program with no state-sized collective, and both the per-device HBM
    footprint and the sketch's int32 extent bound shrink by N (see
    docs/performance.md, "Sliced metrics"). Results, sync, checkpoints and
    merges are BIT-identical to the unsharded collection on the same rows
    (integer lanes exact; float sums under the documented f32 associativity
    contract).

    Everything downstream of ``update`` is the plain
    :class:`MetricCollection` machinery — the shared
    :class:`~torcheval_tpu.metrics.deferred.EvalWindow`, the one donated
    ``window_step`` program, checkpoints, serve eviction, the two-round
    sync — operating on members whose states carry a leading slice axis.
    """

    # serve ingest gate: the id column must stay HOST-side until interning
    # (the staging pass's coalesced H2D would strand it on device and force
    # a readback per batch); slice routing as a staging-pass step is the
    # ROADMAP 3(c) follow-up seam
    _host_ingest_only = True

    def __init__(
        self,
        metrics: Dict[str, Metric],
        *,
        capacity: int = _DEFAULT_CAPACITY,
        curve_bucket_bits: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        mesh_axis: Optional[str] = None,
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = {"metric": metrics}
        if mesh is not None and mesh_axis is None:
            raise ValueError(
                "mesh requires mesh_axis: name the mesh axis the slice "
                "axis shards over."
            )
        if mesh_axis is not None and mesh is None:
            # the serve-wire spelling (slices={"mesh_axis": ...}): an axis
            # NAME alone shards over all local devices in one flat mesh
            mesh = Mesh(np.asarray(jax.devices()), (str(mesh_axis),))
        if mesh is not None:
            mesh_axis = str(mesh_axis)
            if mesh_axis not in mesh.shape:
                raise ValueError(
                    f"mesh_axis {mesh_axis!r} not in mesh axes "
                    f"{tuple(mesh.shape)}."
                )
            shard: Optional[Tuple[Mesh, str]] = (mesh, mesh_axis)
            shards = int(mesh.shape[mesh_axis])
        else:
            shard = None
            shards = 1
        self._slice_shard = shard
        # capacity stays a multiple of the shard count forever (block-range
        # tiles must divide the leading axis evenly); granularity 1 keeps
        # the unsharded schedule byte-identical to before
        self.slice_table = SliceTable(capacity, granularity=shards)
        members = {
            name: _build_member(
                template,
                self.slice_table,
                curve_bucket_bits=curve_bucket_bits,
                shard=shard,
            )
            for name, template in dict(metrics).items()
        }
        super().__init__(members)
        self._single = False  # sliced results are always name-keyed

    # ---------------------------------------------------------------- ingest
    def update(self, slice_ids, *args, **kwargs):
        """One per-cohort batch: ``slice_ids`` (any int64 cohort ids) plus
        the member update columns. A batch rejected DURING growth rolls the
        id table back entirely (the collection stays consistent at its old
        capacity); a batch rejected by column validation after a successful
        growth may leave its new cohort ids registered with default
        (never-updated) state — loud error either way, never silent
        misrouting."""
        if kwargs:
            raise ValueError(
                "SlicedMetricCollection.update takes positional columns "
                "only: (slice_ids, *update_args)."
            )
        if not args:
            raise ValueError(
                "update needs at least one metric column after slice_ids."
            )
        rows = self._intern_and_grow(slice_ids)
        return self._update_impl((rows, *args), None, False)

    def update_placed(self, args: tuple, *, owned: bool = False):
        """Serve ingest entry: ``args[0]`` is the HOST id column (the
        daemon's staging pass leaves sliced tenants on the host path —
        interning needs host bytes), the rest may be host or device."""
        rows = self._intern_and_grow(np.asarray(args[0]))
        return self._update_impl((rows, *args[1:]), None, owned)

    def _intern_and_grow(self, slice_ids) -> np.ndarray:
        """Transactional intern (review finding): if the members REJECT the
        grown capacity (the sliced sketch's int32 extent bound), the table
        rolls back to its pre-batch state — a table grown past the members
        would make every later batch's new cohorts scatter silently out of
        the members' segment range."""
        mark = self.slice_table.mark()
        rows, grew = self.slice_table.intern(slice_ids)
        if grew:
            try:
                self._grow_members()
            except BaseException:
                self.slice_table.rollback(mark)
                raise
        return rows

    def _grow_members(self) -> None:
        # validate EVERY member first (fail closed before any state pads:
        # a sketch member past its int32 segment-index headroom must
        # reject the growth with the collection still consistent)
        for m in self.metrics.values():
            m._check_capacity(self.slice_table.capacity)
        for m in self.metrics.values():
            m._grow_to(self.slice_table.capacity)

    # ---------------------------------------------------------------- merges
    def merge_collections(
        self, others: List["SlicedMetricCollection"]
    ) -> "SlicedMetricCollection":
        """Merge replica sliced collections member-by-member (the
        hot-tenant-splitting fold: replicas' streams sharded by traffic,
        merged by original id at compute). Sources are folded but not
        mutated. Fails CLOSED: the union capacity is validated against
        every member BEFORE any member merges — member merges grow the
        SHARED table, so a later member's rejection (the sliced sketch's
        int32 extent bound) would otherwise strand earlier members merged
        at a capacity the collection cannot roll back."""
        union = self.slice_table.registered_ids()
        for other in others:
            union = np.union1d(union, other.slice_table.registered_ids())
        # SliceTable.predict_growth IS intern's growth schedule, so the
        # predicted capacity is exactly what the merge's interns settle on
        cap = self.slice_table.predict_growth(int(union.shape[0]))
        for m in self.metrics.values():
            m._check_capacity(cap)
        if self._window is not None:
            self._window.close()
        for other in others:
            if other._window is not None:
                other._window.close()
            for name, member in self.metrics.items():
                member.merge_state([other.metrics[name]])
        return self

    def reset(self) -> "SlicedMetricCollection":
        # a collection reset forgets the observed cohort set too (dense
        # capacity stays grown — geometric growth is monotone per instance)
        super().reset()
        self.slice_table.clear()
        return self

    def __deepcopy__(self, memo):
        # share the mesh handle by reference (Device objects do not
        # deepcopy); seeding the memo covers the collection's _slice_shard
        # AND every member's _shard/_fold_params reference to the same mesh
        import copy as _copy

        if self._slice_shard is not None:
            memo[id(self._slice_shard[0])] = self._slice_shard[0]
            memo[id(self._slice_shard)] = self._slice_shard
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        new.__dict__.update(_copy.deepcopy(self.__dict__, memo))
        return new


# ------------------------------------------------------------ sync alignment
# Every member of one collection sync gathers IDENTICAL id lanes (they share
# the SliceTable), so the sorted-union/inverse — a sort over world x count
# ids — is computed once per lane content and reused across members instead
# of once per member. Content-keyed (blake2b over the packed lanes: O(N)
# hash vs O(N log N) sort) so reuse needs no caller plumbing; two entries
# cover interleaved syncs of two collections.
_UNION_CACHE: Dict[Tuple, Tuple] = {}
_UNION_CACHE_MAX = 2


def _union_for_gathered(
    gathered: List[Dict[str, Any]],
) -> Tuple[np.ndarray, np.ndarray, List[int], np.ndarray, np.ndarray]:
    """``(union, inverse, per_rank_counts, union_hi, union_lo)`` for the
    gathered id lanes, memoized on lane content."""
    import hashlib

    key_parts = []
    per_rank = []
    for g in gathered:
        count = int(np.asarray(g["slice_count"]))
        hi = np.ascontiguousarray(np.asarray(g["slice_ids_hi"])[:count])
        lo = np.ascontiguousarray(np.asarray(g["slice_ids_lo"])[:count])
        h = hashlib.blake2b(digest_size=16)
        h.update(hi.tobytes())
        h.update(lo.tobytes())
        key_parts.append((count, h.digest()))
        per_rank.append((hi, lo, count))
    key = tuple(key_parts)
    hit = _UNION_CACHE.pop(key, None)
    if hit is None:
        all_ids = (
            np.concatenate([_unpack_ids(hi, lo) for hi, lo, _ in per_rank])
            if per_rank
            else np.empty(0, np.int64)
        )
        union, inverse = np.unique(all_ids, return_inverse=True)
        union_hi, union_lo = _pack_ids(union)
        hit = (union, inverse, [c for _, _, c in per_rank], union_hi, union_lo)
    _UNION_CACHE[key] = hit  # re-insert: oldest-out when over capacity
    while len(_UNION_CACHE) > _UNION_CACHE_MAX:
        _UNION_CACHE.pop(next(iter(_UNION_CACHE)))
    return hit


def align_sliced_gathered(
    metric: _SlicedMemberBase, gathered: List[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Remap every rank's gathered sliced states onto the SORTED-UNION id
    table before the ordinary per-reduction fold (the toolkit calls this
    from ``get_synced_metric`` when the metric is row-keyed).

    Pure local post-gather work — the union is a deterministic function of
    the gathered id lanes, so every rank computes the identical table and
    the collective count stays exactly the wire's two rounds regardless of
    slice count or per-rank raggedness. Rank rows scatter into
    default-filled ``(U, *S)`` buffers (the reduce identity), after which
    SUM/MAX/MIN fold elementwise as if every rank had always agreed on the
    layout. The id lanes are rewritten to the union on every rank entry, so
    the NONE-reduction fold (and the post-install
    ``_adopt_state_shapes``) see consistent values."""
    union, inverse, per_rank_counts, union_hi, union_lo = (
        _union_for_gathered(gathered)
    )
    u = int(union.shape[0])
    offset = 0
    aligned: List[Dict[str, Any]] = []
    for g, count in zip(gathered, per_rank_counts):
        rows = inverse[offset : offset + count]
        offset += count
        out = dict(g)
        for name in metric._sliced_state_names:
            arr = np.asarray(g[name])
            row_default = np.asarray(metric._row_defaults[name])
            buf = np.broadcast_to(
                row_default.astype(arr.dtype, copy=False),
                (u,) + arr.shape[1:],
            ).copy()
            buf[rows] = arr[:count]
            out[name] = buf
        out["slice_ids_hi"] = union_hi
        out["slice_ids_lo"] = union_lo
        out["slice_count"] = np.int32(u)
        aligned.append(out)
    return aligned

"""HitRate metric. Reference: ``torcheval/metrics/ranking/hit_rate.py``.

Per-sample scores are computed at update time (one fused kernel per batch)
and cached as a list of device arrays; compute concatenates. The cache holds
one float per *sample*, not per class, so memory is O(N) regardless of the
class count.
"""

from __future__ import annotations

from typing import Optional

import jax

from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.utils.devices import DeviceLike


class HitRate(SampleCacheMetric[jax.Array]):
    """Per-sample hit rate of the target class among the top-``k`` predictions.

    Args:
        k: top-k cutoff; ``None`` considers all classes (hit rate 1.0).

    Reference parity: ``ranking/hit_rate.py:19-96``. ``compute()`` returns the
    concatenated per-sample score vector (empty array before any update).
    """

    def __init__(self, *, k: Optional[int] = None, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        if k is not None and k <= 0:
            raise ValueError(f"k should be None or positive, got {k}.")
        self.k = k
        self._add_cache_state("scores")

    def update(self, input, target) -> "HitRate":
        input, target = self._input(input), self._input(target)
        self.scores.append(hit_rate(input, target, k=self.k))
        return self

    def compute(self) -> jax.Array:
        return self._concat_cache("scores")

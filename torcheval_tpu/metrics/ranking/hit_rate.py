"""HitRate metric. Reference: ``torcheval/metrics/ranking/hit_rate.py``.

Per-sample scores are computed at update time (one fused kernel per batch)
and cached as a list of device arrays; compute concatenates. The cache holds
one float per *sample*, not per class, so memory is O(N) regardless of the
class count.

ISSUE 13: ``approx=`` swaps the per-sample cache for a resident value
sketch (``torcheval_tpu.sketch``) — O(buckets) memory forever. The
per-sample vector is then unrepresentable, so ``compute()`` returns the
MEAN hit rate (the quantity the vector is overwhelmingly reduced to),
estimated from the sketch within ``sketch.relative_error(bucket_bits)``
relative error; merges stay exact (bucket add).
"""

from __future__ import annotations

from typing import Optional

import jax

from torcheval_tpu.metrics.functional.ranking.hit_rate import hit_rate
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.sketch import (
    DEFAULT_BUCKET_BITS,
    ValueSketchCacheMixin,
    mean_from_counts,
    resolve_approx,
)
from torcheval_tpu.utils.devices import DeviceLike


class HitRate(ValueSketchCacheMixin, SampleCacheMetric[jax.Array]):
    """Per-sample hit rate of the target class among the top-``k`` predictions.

    Args:
        k: top-k cutoff; ``None`` considers all classes (hit rate 1.0).
        approx: opt into resident-sketch state (module docstring);
            ``compute()`` then returns the mean hit rate.

    Reference parity: ``ranking/hit_rate.py:19-96``. ``compute()`` returns the
    concatenated per-sample score vector (empty array before any update)
    in exact mode.
    """

    def __init__(
        self,
        *,
        k: Optional[int] = None,
        approx=None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        if k is not None and k <= 0:
            raise ValueError(f"k should be None or positive, got {k}.")
        self.k = k
        self._add_cache_state("scores")
        bits = resolve_approx(approx, default_bits=DEFAULT_BUCKET_BITS)
        if bits is not None:
            self._init_value_sketch(bits, "scores")

    def update(self, input, target) -> "HitRate":
        input, target = self._input(input), self._input(target)
        batch = hit_rate(input, target, k=self.k)
        self.scores.append(batch)
        if self._sketch_enabled():
            self._sketch_stage(batch)
        return self

    def compute(self) -> jax.Array:
        if self._sketch_enabled():
            counts, nan, overflow = self._sketch_counts_parts()
            result = mean_from_counts(counts, self._sketch_bits)
            from torcheval_tpu.sketch.cache import raise_sketch_overflow

            raise_sketch_overflow(overflow)
            self._sketch_check_nan(nan)
            return result
        return self._concat_cache("scores")

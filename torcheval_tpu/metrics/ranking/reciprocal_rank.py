"""ReciprocalRank metric. Reference:
``torcheval/metrics/ranking/reciprocal_rank.py``.

ISSUE 13: ``approx=`` swaps the per-sample cache for a resident value
sketch; ``compute()`` then returns the MEAN reciprocal rank (MRR) within
``sketch.relative_error(bucket_bits)`` relative error — see
``ranking/hit_rate.py`` for the shared contract."""

from __future__ import annotations

from typing import Optional

import jax

from torcheval_tpu.metrics.functional.ranking.reciprocal_rank import reciprocal_rank
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.sketch import (
    DEFAULT_BUCKET_BITS,
    ValueSketchCacheMixin,
    mean_from_counts,
    resolve_approx,
)
from torcheval_tpu.utils.devices import DeviceLike


class ReciprocalRank(ValueSketchCacheMixin, SampleCacheMetric[jax.Array]):
    """Per-sample ``1 / (rank+1)`` of the target class (0 beyond ``k``).

    Args:
        k: optional top-k cutoff.
        approx: opt into resident-sketch state; ``compute()`` then returns
            the mean reciprocal rank.

    Reference parity: ``ranking/reciprocal_rank.py:20-100``.
    """

    def __init__(
        self,
        *,
        k: Optional[int] = None,
        approx=None,
        device: DeviceLike = None,
    ) -> None:
        super().__init__(device=device)
        if k is not None and k <= 0:
            raise ValueError(f"k should be None or positive, got {k}.")
        self.k = k
        self._add_cache_state("scores")
        bits = resolve_approx(approx, default_bits=DEFAULT_BUCKET_BITS)
        if bits is not None:
            self._init_value_sketch(bits, "scores")

    def update(self, input, target) -> "ReciprocalRank":
        input, target = self._input(input), self._input(target)
        batch = reciprocal_rank(input, target, k=self.k)
        self.scores.append(batch)
        if self._sketch_enabled():
            self._sketch_stage(batch)
        return self

    def compute(self) -> jax.Array:
        if self._sketch_enabled():
            counts, nan, overflow = self._sketch_counts_parts()
            result = mean_from_counts(counts, self._sketch_bits)
            from torcheval_tpu.sketch.cache import raise_sketch_overflow

            raise_sketch_overflow(overflow)
            self._sketch_check_nan(nan)
            return result
        return self._concat_cache("scores")

"""ReciprocalRank metric. Reference:
``torcheval/metrics/ranking/reciprocal_rank.py``."""

from __future__ import annotations

from typing import Optional

import jax

from torcheval_tpu.metrics.functional.ranking.reciprocal_rank import reciprocal_rank
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.utils.devices import DeviceLike


class ReciprocalRank(SampleCacheMetric[jax.Array]):
    """Per-sample ``1 / (rank+1)`` of the target class (0 beyond ``k``).

    Args:
        k: optional top-k cutoff.

    Reference parity: ``ranking/reciprocal_rank.py:20-100``.
    """

    def __init__(self, *, k: Optional[int] = None, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        if k is not None and k <= 0:
            raise ValueError(f"k should be None or positive, got {k}.")
        self.k = k
        self._add_cache_state("scores")

    def update(self, input, target) -> "ReciprocalRank":
        input, target = self._input(input), self._input(target)
        self.scores.append(reciprocal_rank(input, target, k=self.k))
        return self

    def compute(self) -> jax.Array:
        return self._concat_cache("scores")

"""MAP@k metric (ISSUE 14): mean truncated average precision over rows with
at least one relevant label, riding the deferred window-step with scalar SUM
state — see ``metrics/ranking/_retrieval.py`` for the shared contract and
``functional/ranking/retrieval.py`` for the per-sample math."""

from __future__ import annotations

from torcheval_tpu.metrics.functional.ranking.retrieval import _map_kernel
from torcheval_tpu.metrics.ranking._retrieval import (
    RetrievalMeanMetric,
    valid_mean_deltas,
)


def _map_fold(input, target, k, topk_method, label_mesh):
    return valid_mean_deltas(
        _map_kernel(input, target, k, topk_method, label_mesh)
    )


class MAP(RetrievalMeanMetric):
    """Mean MAP@k: ``(1/min(m, k)) · Σ_j rel_j · precision@j`` per row
    (``m`` = the row's relevant-label count); rows with no relevant label
    are excluded. Constructor arguments and state as
    :class:`~torcheval_tpu.metrics.ranking.NDCG`."""

    _fold_fn = staticmethod(_map_fold)


__all__ = ["MAP"]

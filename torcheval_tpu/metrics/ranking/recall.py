"""Recall@k metric (ISSUE 14): mean top-k recall over rows with at least
one relevant label, riding the deferred window-step with scalar SUM state —
see ``metrics/ranking/_retrieval.py`` for the shared contract and
``functional/ranking/retrieval.py`` for the per-sample math."""

from __future__ import annotations

from torcheval_tpu.metrics.functional.ranking.retrieval import _recall_kernel
from torcheval_tpu.metrics.ranking._retrieval import (
    RetrievalMeanMetric,
    valid_mean_deltas,
)


def _recall_fold(input, target, k, topk_method, label_mesh):
    return valid_mean_deltas(
        _recall_kernel(input, target, k, topk_method, label_mesh)
    )


class RecallAtK(RetrievalMeanMetric):
    """Mean Recall@k: ``|top-k ∩ relevant| / |relevant|`` per row; rows with
    no relevant label are excluded. Constructor arguments and state as
    :class:`~torcheval_tpu.metrics.ranking.NDCG`. (Named ``RecallAtK`` — the
    classification namespace already owns ``BinaryRecall`` /
    ``MulticlassRecall``.)"""

    _fold_fn = staticmethod(_recall_fold)


__all__ = ["RecallAtK"]

"""NDCG@k metric (ISSUE 14): mean normalized discounted cumulative gain over
rows with a positive ideal DCG, riding the deferred window-step with scalar
SUM state — see ``metrics/ranking/_retrieval.py`` for the shared contract
and ``functional/ranking/retrieval.py`` for the per-sample math."""

from __future__ import annotations

import jax

from torcheval_tpu.metrics.functional.ranking.retrieval import _ndcg_kernel
from torcheval_tpu.metrics.ranking._retrieval import (
    RetrievalMeanMetric,
    valid_mean_deltas,
)


# module-level fold fn: shared identity keys the deferred-fold jit cache
# across metric instances (metrics/deferred.py)
def _ndcg_fold(input, target, k, topk_method, label_mesh):
    return valid_mean_deltas(
        _ndcg_kernel(input, target, k, topk_method, label_mesh)
    )


class NDCG(RetrievalMeanMetric):
    """Mean NDCG@k: linear graded gains, ``1/log2(rank+2)`` discounts,
    per-row ideal-DCG normalization; rows with zero ideal DCG are excluded.

    Args:
        k: cutoff; ``None`` ranks every label.
        topk_method: streaming top-k engine lowering (``ops/topk.py``) for
            both the score ranking and the ideal relevance ranking.
        label_mesh: optional ``(mesh, label_axis_name)`` — or ``(mesh,
            label_axis_name, batch_axes)`` to keep rows sharded on
            batch × label meshes — the fold's engine calls run
            label-sharded (extreme-vocabulary L; the label axis is never
            replicated). Axis names validate eagerly at construction.

    State: ``score_sum`` (f32) + ``num_valid`` (i32), both SUM — merges,
    toolkit sync and checkpoints are exact scalar adds.
    """

    _fold_fn = staticmethod(_ndcg_fold)


__all__ = ["NDCG"]

from torcheval_tpu.metrics.ranking.hit_rate import HitRate
from torcheval_tpu.metrics.ranking.map import MAP
from torcheval_tpu.metrics.ranking.ndcg import NDCG
from torcheval_tpu.metrics.ranking.recall import RecallAtK
from torcheval_tpu.metrics.ranking.reciprocal_rank import ReciprocalRank

__all__ = ["HitRate", "MAP", "NDCG", "RecallAtK", "ReciprocalRank"]

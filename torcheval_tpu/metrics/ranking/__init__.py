from torcheval_tpu.metrics.ranking.hit_rate import HitRate
from torcheval_tpu.metrics.ranking.reciprocal_rank import ReciprocalRank

__all__ = ["HitRate", "ReciprocalRank"]

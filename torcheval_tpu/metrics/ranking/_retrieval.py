"""Shared deferred-fold base for the retrieval family (NDCG@k / MAP@k /
Recall@k — ISSUE 14).

Unlike the per-sample-cache ranking metrics (``HitRate``,
``ReciprocalRank``), the retrieval metrics are MEAN metrics over valid rows:
their state is two scalars (``score_sum`` f32 + ``num_valid`` i32, both
``Reduction.SUM``), so

* updates ride :class:`~torcheval_tpu.metrics.deferred.DeferredFoldMixin`
  exactly like the counter families — O(1) host appends, one fused
  window-step program per budget window, terminal compute inside the same
  program (``_compute_fn``);
* toolkit sync / ``merge_state`` / checkpoints need no new machinery — two
  scalar SUM lanes on the existing typed wire;
* memory is O(1) at any L: the label axis lives only inside the fold's
  top-k engine call (``topk_method`` / ``label_mesh`` threaded through
  ``_fold_params``), never in state.

``label_mesh=(mesh, axis_name)`` opts the fold's engine calls into the
label-sharded decomposition (``ops/topk.py::sharded_label_topk``) — the
fold runs inside jit where operand shardings are invisible, so the mesh
must be threaded explicitly. Both entries are hashable, which is what lets
them ride the static ``_fold_params`` tuple.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.deferred import DeferredFoldMixin
from torcheval_tpu.metrics.functional.ranking.retrieval import (
    _check_label_mesh,
    _retrieval_input_check,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, zeros_state
from torcheval_tpu.utils.devices import DeviceLike


def _mean_compute(score_sum: jax.Array, num_valid: jax.Array) -> jax.Array:
    """Mean over valid rows; NaN before the first valid row (the empty-read
    convention of the per-sample family)."""
    return jnp.where(
        num_valid > 0,
        score_sum / jnp.maximum(num_valid, 1).astype(jnp.float32),
        jnp.nan,
    )


def valid_mean_deltas(per_sample: jax.Array) -> dict:
    """One batch's ``{score_sum, num_valid}`` deltas from a NaN-poisoned
    per-sample score vector — the shared tail of every retrieval fold fn."""
    valid = ~jnp.isnan(per_sample)
    return {
        "score_sum": jnp.sum(jnp.where(valid, per_sample, 0.0)),
        "num_valid": jnp.sum(valid.astype(jnp.int32)),
    }


class RetrievalMeanMetric(DeferredFoldMixin, Metric[jax.Array]):
    """Deferred mean-over-valid-rows retrieval metric; subclasses set
    ``_fold_fn`` (a module-level kernel returning
    :func:`valid_mean_deltas`)."""

    _fold_per_chunk = True
    # the engine's sharded lowerings (custom_partitioning / shard_map) have
    # no jax.vmap batching rule — multi-chunk stacked folds keep the
    # sequential lax.scan body instead (same choice as TopKMultilabelAccuracy)
    _fold_vmap = False
    _compute_fn = staticmethod(_mean_compute)

    def __init__(
        self,
        *,
        k: Optional[int] = None,
        topk_method: str = "auto",
        label_mesh: Optional[Tuple] = None,
        device: DeviceLike = None,
    ) -> None:
        # validate the engine knobs EAGERLY (updates defer — a typo must not
        # surface only at compute(), after the stream was accepted)
        from torcheval_tpu.ops.topk import _LOCAL_METHODS

        if k is not None and (type(k) is not int or k <= 0):
            raise ValueError(f"k should be None or a positive int, got {k!r}.")
        if topk_method not in _LOCAL_METHODS:
            raise ValueError(
                f"topk_method must be one of {_LOCAL_METHODS}, got "
                f"{topk_method!r}."
            )
        _check_label_mesh(label_mesh)
        if label_mesh is not None and device is None:
            # the fold's shard_map spans the whole mesh, so the window-step
            # program's states must live there too: bind the metric
            # mesh-replicated (scalar states — replication is 8 bytes). A
            # caller-provided device/sharding wins when given; it must span
            # the same device set.
            from jax.sharding import NamedSharding, PartitionSpec

            device = NamedSharding(label_mesh[0], PartitionSpec())
        super().__init__(device=device)
        self.k = k
        self.topk_method = topk_method
        self.label_mesh = label_mesh
        self._add_state(
            "score_sum", zeros_state((), dtype=jnp.float32),
            reduction=Reduction.SUM,
        )
        self._add_state(
            "num_valid", zeros_state((), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )
        self._init_deferred()
        self._fold_params = (k, topk_method, label_mesh)

    def _update_check(self, input, target) -> None:
        # shape-only: memoised per batch signature by the _defer fast path
        _retrieval_input_check(input, target, self.k)

    def update(self, input, target):
        self._defer(self._input(input), self._input(target))
        return self

    def compute(self) -> jax.Array:
        return self._deferred_compute()

    def merge_state(self, metrics: Iterable["RetrievalMeanMetric"]):
        metrics = list(metrics)
        self._fold_now()
        for metric in metrics:
            metric._fold_now()
        for metric in metrics:
            self.score_sum = self.score_sum + jax.device_put(
                metric.score_sum, self.device
            )
            self.num_valid = self.num_valid + jax.device_put(
                metric.num_valid, self.device
            )
        return self

from torcheval_tpu.metrics import functional
from torcheval_tpu.metrics.aggregation import Cat, Max, Mean, Min, Sum, Throughput
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction

__all__ = [
    # base interface
    "Metric",
    "Reduction",
    # functional metrics
    "functional",
    # class metrics
    "Cat",
    "Max",
    "Mean",
    "Min",
    "Sum",
    "Throughput",
]

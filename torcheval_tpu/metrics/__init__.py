from torcheval_tpu.metrics import functional
from torcheval_tpu.metrics.aggregation import Cat, Max, Mean, Min, Sum, Throughput
from torcheval_tpu.metrics.classification import (
    BinaryAccuracy,
    BinaryAUPRC,
    BinaryAUROC,
    BinaryBinnedPrecisionRecallCurve,
    BinaryConfusionMatrix,
    BinaryF1Score,
    BinaryNormalizedEntropy,
    BinaryPrecision,
    BinaryPrecisionRecallCurve,
    BinaryRecall,
    ClickThroughRate,
    MulticlassAccuracy,
    MulticlassAUPRC,
    MulticlassAUROC,
    MulticlassBinnedPrecisionRecallCurve,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassPrecisionRecallCurve,
    MulticlassRecall,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
    WeightedCalibration,
    WindowedClickThroughRate,
    WindowedWeightedCalibration,
)
from torcheval_tpu.metrics.collection import MetricCollection
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.ranking import HitRate, ReciprocalRank
from torcheval_tpu.metrics.regression import MeanSquaredError, R2Score
from torcheval_tpu.metrics.state import Reduction

__all__ = [
    # base interface
    # class metrics
    # functional metrics
    "BinaryAccuracy",
    "BinaryAUPRC",
    "BinaryAUROC",
    "BinaryBinnedPrecisionRecallCurve",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryNormalizedEntropy",
    "BinaryPrecision",
    "BinaryPrecisionRecallCurve",
    "BinaryRecall",
    "ClickThroughRate",
    "Cat",
    "functional",
    "HitRate",
    "Max",
    "Mean",
    "MeanSquaredError",
    "Metric",
    "MetricCollection",
    "Min",
    "MulticlassAccuracy",
    "MulticlassAUPRC",
    "MulticlassAUROC",
    "MulticlassBinnedPrecisionRecallCurve",
    "MulticlassConfusionMatrix",
    "MulticlassF1Score",
    "MulticlassPrecision",
    "MulticlassPrecisionRecallCurve",
    "MulticlassRecall",
    "MultilabelAccuracy",
    "R2Score",
    "ReciprocalRank",
    "Reduction",
    "Sum",
    "Throughput",
    "TopKMultilabelAccuracy",
    "WeightedCalibration",
    "WindowedClickThroughRate",
    "WindowedWeightedCalibration",
]

"""`EvalRouter`: tenant placement, health probing, and cross-host migration.

The cluster layer of ISSUE 10. A router fronts N eval-service hosts (each
an :class:`EvalServer` + :class:`EvalDaemon` pair sharing one checkpoint
root) with one :class:`~torcheval_tpu.serve.EvalClient` per endpoint, and
makes the death of any single host a routine event (the TPU-serving
stance: host loss and draining are absorbed, not outages):

* **placement** — tenants place by rendezvous (highest-random-weight)
  hashing of ``tenant_id`` over the *alive* endpoint set: deterministic,
  coordination-free, and minimal-movement (a host's death moves only its
  own tenants, never reshuffles survivors);
* **health probing** — ``health()`` probes every alive host's
  ``daemon.health()`` over the wire; a probe failure (or any transport
  failure on a tenant op) marks the host dead and triggers migration;
* **failure migration** — a dead host's tenants re-``attach`` on a
  surviving host with ``resume="auto"``: the daemon restores each
  tenant's latest checkpoint from the shared root (``resilience.save``'s
  contract is location-independent — evict-on-idle and flushes already
  write there) and re-arms its dedup watermark from the checkpoint
  manifest; the router then replays the client-side replay buffer's
  un-durable tail. Acked-and-checkpointed batches come back through the
  checkpoint, un-acked ones through replay, and seq dedup absorbs the
  overlap — post-migration computes match a fault-free oracle
  bit-identically;
* **graceful drain** — ``drain(endpoint)`` asks the host to
  checkpoint-and-evict every tenant (it stops admitting immediately),
  then migrates them the same way; use it before planned maintenance so
  the "un-acked tail" is empty and the blackout is one restore long.

Transport knobs ride through ``**client_kwargs`` to every per-host
client: ``pipeline_depth=`` turns on ISSUE 18's deferred-ack submit
pipelining against hosts that grant it (a migrated tenant's replay
drains through the ordinary lock-step path first, then new submits
pipeline to the survivor), and ``local_transport=False`` forces TCP
even when a fronted server shares this process (the bench's migration
leg pins it off so the blackout measured is the wire's).

Observability: ``serve.router.migrations{reason=}``,
``serve.router.replays{tenant=}`` (counted at the replaying client),
``serve.router.probe_failures{endpoint=}``, plus a
``serve.router.migrate`` span per migrated host (a migration-blackout
bar in the Chrome trace).

Fleet telemetry (ISSUE 16): ``subscribe_obs()`` opens one obs push
stream per alive host (``EvalClient.subscribe_obs`` — delta snapshots +
``load_report`` on the server's timer, degrading to ``health()`` polling
against old peers); the router folds each host's deltas into a
:class:`~torcheval_tpu.obs.DeltaAccumulator` and keeps its latest load
report. ``fleet_status()`` serves the folded view with staleness marking
(a host whose last push is older than ``stale_after_s`` — default three
push intervals — is ``stale`` BEFORE the failure detector evicts it);
``fleet_chrome_trace()`` merges every host's pushed timeline events into
one Chrome trace, pid per host. None of it adds collective rounds: the
stream rides the serve wire, not the toolkit funnel.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.serve.client import EvalClient
from torcheval_tpu.serve.errors import AdmissionError, ServeError, WireError

_logger = logging.getLogger(__name__)

__all__ = ["EvalRouter"]


class _RoutedTenant:
    __slots__ = ("spec", "knobs", "endpoint")

    def __init__(self, spec: Any, knobs: Dict[str, Any], endpoint: str):
        self.spec = spec
        self.knobs = knobs
        self.endpoint = endpoint


class EvalRouter:
    """Route tenants across eval-service hosts; survive any one of them.

    ``endpoints`` are ``"host:port"`` strings (or ``(host, port)``
    tuples); ``client_kwargs`` configure every per-host
    :class:`EvalClient` (deadlines, breaker, replay capacity — all
    validated there). The hosts must share one checkpoint root (each
    daemon's ``evict_dir``) for migration to have a resume source.

    Thread-safe for the many-producers shape: submits for different
    tenants proceed concurrently (per-tenant client locks); migration
    holds the router lock so a failing host is migrated exactly once.
    """

    def __init__(
        self,
        endpoints: Sequence[Any],
        *,
        client_factory: Any = EvalClient,
        reroute_grace_s: float = 60.0,
        probe_timeout_s: Optional[float] = 5.0,
        **client_kwargs: Any,
    ) -> None:
        if not endpoints:
            raise ValueError("EvalRouter needs at least one endpoint.")
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        for knob, value in (
            ("reroute_grace_s", reroute_grace_s),
            ("probe_timeout_s", probe_timeout_s),
        ):
            try:
                _check_timeout_s(value)
            except ValueError as e:
                raise ValueError(f"{knob}: {e}") from None
        if reroute_grace_s is None:
            raise ValueError("reroute_grace_s must be a positive number.")
        self._reroute_grace_s = float(reroute_grace_s)
        self._probe_timeout_s = probe_timeout_s
        self._clients: Dict[str, EvalClient] = {}
        for ep in endpoints:
            client = client_factory(ep, **client_kwargs)
            self._clients[client.endpoint] = client
        if len(self._clients) != len(endpoints):
            raise ValueError(f"duplicate endpoints in {endpoints!r}.")
        self._alive = set(self._clients)
        self._tenants: Dict[str, _RoutedTenant] = {}
        self._lock = threading.RLock()
        # endpoints whose migration is in flight: the lock guards only
        # the routing tables; migration's network work (attach + restore
        # + replay per tenant) runs OUTSIDE it so one dying host never
        # stalls traffic to healthy hosts. _cv wakes threads waiting for
        # an in-flight migration to finish.
        self._cv = threading.Condition(self._lock)
        self._migrating: set = set()
        # fleet telemetry (ISSUE 16): per-endpoint folded obs state,
        # guarded by its own lock — push callbacks run on subscriber
        # threads and must never contend with migration's router lock
        self._fleet_lock = threading.Lock()
        self._obs_subs: Dict[str, Any] = {}
        self._fleet: Dict[str, Dict[str, Any]] = {}
        self._obs_interval_s: Optional[float] = None
        self._stale_after_s: Optional[float] = None
        self._fleet_max_events = 4096

    # ------------------------------------------------------------ placement
    def _place(self, tenant_id: str) -> str:
        """Rendezvous placement over the alive set (deterministic for a
        given alive set; no state to rebalance when hosts die)."""
        with self._lock:
            alive = sorted(self._alive)
        if not alive:
            raise ServeError(
                "no_hosts", "every endpoint is dead or drained."
            )
        return max(
            alive,
            key=lambda ep: hashlib.sha256(
                f"{tenant_id}@{ep}".encode()
            ).digest(),
        )

    @property
    def endpoints(self) -> List[str]:
        return sorted(self._clients)

    @property
    def alive(self) -> List[str]:
        with self._lock:
            return sorted(self._alive)

    def placement(self) -> Dict[str, str]:
        """Current ``{tenant_id: endpoint}`` map."""
        with self._lock:
            return {t: rec.endpoint for t, rec in self._tenants.items()}

    def close(self) -> None:
        self.unsubscribe_obs()
        for client in self._clients.values():
            client.close()

    def __enter__(self) -> "EvalRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ tenant api
    def attach(
        self, tenant_id: str, spec: Dict[str, Any], **knobs: Any
    ) -> str:
        """Place and attach one tenant; returns the chosen endpoint.
        ``spec``/``knobs`` are recorded so a migration can re-attach the
        tenant identically elsewhere."""
        with self._lock:
            if tenant_id in self._tenants:
                raise ServeError(
                    "duplicate_tenant",
                    f"tenant {tenant_id!r} is already routed.",
                )
        while True:
            ep = self._place(tenant_id)
            try:
                self._clients[ep].attach(tenant_id, spec, **knobs)
            except WireError as e:
                if not e.retryable:
                    raise
                self._host_failed(ep, cause=e)
                continue
            except AdmissionError as e:
                if e.reason != "draining":
                    raise
                # the rendezvous pick is mid-decommission: treat it like
                # a failed host (same single-flight migration machinery;
                # if the router's own drain() already owns the move this
                # just waits for it) and re-place among the survivors
                self._host_failed(ep, cause=e)
                continue
            with self._lock:
                self._tenants[tenant_id] = _RoutedTenant(spec, dict(knobs), ep)
            return ep

    def _routed(self, tenant_id: str) -> _RoutedTenant:
        with self._lock:
            rec = self._tenants.get(tenant_id)
        if rec is None:
            raise ServeError(
                "unknown_tenant",
                f"tenant {tenant_id!r} is not routed; attach it first.",
            )
        return rec

    def _with_failover(self, tenant_id: str, op) -> Any:
        """Run one tenant op against its current host; on a transport
        failure, migrate the host's tenants and run the op once more on
        the new placement (compute/flush/detach are idempotent). A second
        transport failure surfaces. The in-flight-migration window
        (``tenant_migrated`` / client-side ``unknown_tenant`` for a
        still-routed tenant) re-routes within ``reroute_grace_s``, like
        ``submit``."""
        wire_failures = 0
        deadline = time.monotonic() + self._reroute_grace_s
        sleep_s = 0.02
        while True:
            rec = self._routed(tenant_id)
            client = self._clients[rec.endpoint]
            try:
                return op(client)
            except WireError as e:
                wire_failures += 1
                if wire_failures >= 2 or not e.retryable:
                    # a protocol error (version skew) is not evidence the
                    # HOST is dead — don't let it trigger a migration
                    raise
                self._host_failed(rec.endpoint, cause=e)
            except ServeError as e:
                if e.reason == "tenant_migrated" or (
                    e.reason == "unknown_tenant"
                    and tenant_id in self._tenants
                ):
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(sleep_s)
                    sleep_s = min(sleep_s * 2, 0.5)
                    continue
                raise

    def submit(self, tenant_id: str, *args: Any, **kw: Any) -> bool:
        """Deliver one batch, surviving a host death or drain mid-submit.

        A transport-failed submit whose batch was already booked in the
        client replay buffer is delivered BY the migration's replay —
        resubmitting it here under a fresh seq would apply it twice, so
        failover only resubmits when the failure struck before booking.
        Three structured rejects mean "the placement is changing, the
        batch was NOT booked; wait and re-route" and are absorbed up to
        ``reroute_grace_s``: ``tenant_migrated`` (a concurrent migration
        exported the client state first), client-side ``unknown_tenant``
        for a tenant the ROUTER still routes (the export-to-adopt window
        of an in-flight migration), and ``draining`` (planned
        decommission; the drain's own migration moves the tenant — a
        drain issued behind the router's back never migrates, so the
        grace period bounds that misuse with a structured error)."""
        wire_failures = 0
        deadline = time.monotonic() + self._reroute_grace_s
        sleep_s = 0.02
        while True:
            rec = self._routed(tenant_id)
            client = self._clients[rec.endpoint]
            try:
                return client.submit(tenant_id, *args, **kw)
            except WireError as e:
                wire_failures += 1
                if wire_failures >= 2 or not e.retryable:
                    raise
                self._host_failed(rec.endpoint, cause=e)
                if getattr(e, "batch_booked", False):
                    # delivery is the migration replay's job — but only a
                    # migration that SUCCEEDED for this tenant (it is
                    # still routed) actually replayed it; a dropped
                    # tenant's batch is gone and saying True would lie
                    with self._lock:
                        still_routed = tenant_id in self._tenants
                    if still_routed:
                        return True
                    raise ServeError(
                        "migration_failed",
                        f"tenant {tenant_id!r} could not be migrated off "
                        f"{rec.endpoint}; the in-flight batch was lost "
                        "with it.",
                    ) from e
            except ServeError as e:
                if getattr(e, "batch_booked", False):
                    # the batch sits in the replay buffer under its seq
                    # (an earlier ambiguous attempt may have been
                    # admitted): it must be delivered by a MIGRATION'S
                    # replay, never resubmitted fresh. Wait for the
                    # tenant to move off this endpoint within the grace
                    # budget; if nothing moves it, surface the error
                    # (the booking stays, a later migration still
                    # delivers exactly once).
                    old_ep = rec.endpoint
                    while time.monotonic() < deadline:
                        self._wait_not_migrating(old_ep, timeout_s=1.0)
                        with self._lock:
                            cur = self._tenants.get(tenant_id)
                        if cur is None:
                            raise ServeError(
                                "migration_failed",
                                f"tenant {tenant_id!r} was dropped while "
                                "its in-flight batch awaited migration.",
                            ) from e
                        if cur.endpoint != old_ep:
                            return True  # migrated: the replay carried it
                        time.sleep(sleep_s)
                        sleep_s = min(sleep_s * 2, 0.5)
                    raise
                if e.reason == "tenant_migrated" or (
                    e.reason == "unknown_tenant"
                    and tenant_id in self._tenants
                ):
                    pass  # re-route (possibly after the wait below)
                elif e.reason == "draining":
                    self._wait_not_migrating(rec.endpoint, timeout_s=5.0)
                else:
                    raise
                if time.monotonic() >= deadline:
                    raise ServeError(
                        "reroute_storm",
                        f"tenant {tenant_id!r}: submit could not settle "
                        f"on a host within {self._reroute_grace_s}s of "
                        "migrations/drains.",
                    ) from e
                time.sleep(sleep_s)
                sleep_s = min(sleep_s * 2, 0.5)

    def compute(self, tenant_id: str, **kw: Any) -> Any:
        return self._with_failover(
            tenant_id, lambda c: c.compute(tenant_id, **kw)
        )

    def sync_compute(self, tenant_id: str, **kw: Any) -> Any:
        return self._with_failover(
            tenant_id, lambda c: c.sync_compute(tenant_id, **kw)
        )

    def flush(self, tenant_id: str, **kw: Any) -> dict:
        return self._with_failover(
            tenant_id, lambda c: c.flush(tenant_id, **kw)
        )

    def detach(self, tenant_id: str, **kw: Any) -> Optional[str]:
        try:
            return self._with_failover(
                tenant_id, lambda c: c.detach(tenant_id, **kw)
            )
        finally:
            with self._lock:
                self._tenants.pop(tenant_id, None)

    # --------------------------------------------------------------- health
    def health(
        self, *, migrate: bool = True, timeout_s: Any = None
    ) -> Dict[str, Any]:
        """Probe every alive host's ``daemon.health()``. A failed probe
        counts ``serve.router.probe_failures{endpoint=}`` and (with
        ``migrate=True``) marks the host dead and migrates its tenants
        right away — a monitoring loop doubles as the failure detector.
        Probes run single-attempt under ``probe_timeout_s`` (overridable
        via ``timeout_s``): one partitioned host must not blind the
        detector to the others for a whole retry ladder. Returns per-host
        health (``None`` for failed probes), the alive set, and the
        tenant placement."""
        probe_timeout = (
            timeout_s if timeout_s is not None else self._probe_timeout_s
        )
        hosts: Dict[str, Any] = {}
        for ep in self.alive:
            try:
                hosts[ep] = self._clients[ep].health(
                    timeout_s=probe_timeout, attempts=1
                )
            except (WireError, ServeError) as e:
                hosts[ep] = None
                if _obs._enabled:
                    _obs.counter(
                        "serve.router.probe_failures", endpoint=ep
                    )
                _logger.warning(
                    "router: health probe of %s failed: %s", ep, e
                )
                if migrate:
                    self._host_failed(ep, cause=e)
        return {
            "hosts": hosts,
            "alive": self.alive,
            "tenants": self.placement(),
        }

    # ------------------------------------------------------ fleet telemetry
    def subscribe_obs(
        self,
        interval_s: float = 1.0,
        *,
        stale_after_s: Optional[float] = None,
        max_events: int = 4096,
    ) -> Dict[str, str]:
        """Open one obs push stream per alive host (ISSUE 16) and fold
        what arrives into the router's fleet view.

        Each host streams O(changed) registry deltas + timeline events +
        its structured ``load_report`` on its own timer; an old host that
        rejects the op degrades to ``health()`` polling on the same
        cadence (``mode == "poll"``). ``stale_after_s`` (default three
        push intervals) is the staleness horizon :meth:`fleet_status`
        marks hosts against. Returns ``{endpoint: mode}``. Idempotent:
        re-subscribing first drops the existing streams."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        _check_timeout_s(interval_s)
        if stale_after_s is None:
            stale_after_s = 3.0 * float(interval_s)
        _check_timeout_s(stale_after_s)
        if max_events < 0:
            raise ValueError(f"max_events must be >= 0, got {max_events}.")
        self.unsubscribe_obs()
        with self._fleet_lock:
            self._obs_interval_s = float(interval_s)
            self._stale_after_s = float(stale_after_s)
            self._fleet_max_events = int(max_events)
        modes: Dict[str, str] = {}
        for ep in self.alive:
            try:
                sub = self._clients[ep].subscribe_obs(
                    interval_s,
                    on_push=lambda msg, _ep=ep: self._on_obs_push(_ep, msg),
                )
            except (WireError, ServeError) as e:
                _logger.warning(
                    "router: obs subscription to %s failed: %s", ep, e
                )
                continue
            with self._fleet_lock:
                self._obs_subs[ep] = sub
            modes[ep] = sub.mode
        return modes

    def unsubscribe_obs(self) -> None:
        """Stop every obs stream (folded fleet state is kept)."""
        with self._fleet_lock:
            subs, self._obs_subs = self._obs_subs, {}
        for sub in subs.values():
            sub.stop()

    def _on_obs_push(self, endpoint: str, msg: Dict[str, Any]) -> None:
        """Fold one pushed (or polled) obs message into the fleet view.
        Runs on the subscription's thread — only ``_fleet_lock`` here."""
        from torcheval_tpu.obs.stream import DeltaAccumulator

        with self._fleet_lock:
            rec = self._fleet.get(endpoint)
            if rec is None:
                rec = {
                    "acc": DeltaAccumulator(),
                    "events": [],
                    "events_trimmed": 0,
                    "report": None,
                    "received_at": 0.0,
                    "mode": "poll",
                    "pushes": 0,
                }
                self._fleet[endpoint] = rec
            rec["mode"] = (
                "push" if msg.get("op") == "obs_push" else "poll"
            )
            rec["received_at"] = time.monotonic()
            rec["pushes"] += 1
            if msg.get("load_report") is not None:
                rec["report"] = msg["load_report"]
            delta = msg.get("delta")
            if delta:
                rec["acc"].apply(delta)
                events = delta.get("events") or ()
                if events:
                    rec["events"].extend(events)
                    overflow = (
                        len(rec["events"]) - self._fleet_max_events
                    )
                    if overflow > 0:
                        del rec["events"][:overflow]
                        rec["events_trimmed"] += overflow
                rec["events_trimmed"] += int(
                    delta.get("events_trimmed", 0)
                )

    def fleet_status(
        self, *, stale_after_s: Optional[float] = None
    ) -> Dict[str, Any]:
        """The folded fleet view: per-host latest ``load_report``, push
        age, and a ``stale`` flag (no load report yet, or the last one is
        older than ``stale_after_s``). A killed host goes stale here
        within one horizon — BEFORE a health probe or tenant op marks it
        dead — which is the point: the stream is the early-warning
        channel, the failure detector stays authoritative for eviction.
        Pure local fold; no network, no collective rounds."""
        if stale_after_s is None:
            stale_after_s = self._stale_after_s
        if stale_after_s is None:
            stale_after_s = 3.0  # fleet view without an active stream
        now = time.monotonic()
        alive = set(self.alive)
        hosts: Dict[str, Any] = {}
        with self._fleet_lock:
            endpoints = set(self._fleet) | set(self._obs_subs)
            for ep in sorted(endpoints | alive):
                rec = self._fleet.get(ep)
                sub = self._obs_subs.get(ep)
                age = (
                    now - rec["received_at"]
                    if rec is not None and rec["received_at"]
                    else None
                )
                hosts[ep] = {
                    "alive": ep in alive,
                    "mode": rec["mode"] if rec else (
                        sub.mode if sub is not None else None
                    ),
                    "subscribed": sub is not None,
                    "age_s": age,
                    "stale": age is None or age > stale_after_s,
                    "load_report": rec["report"] if rec else None,
                    "pushes": rec["pushes"] if rec else 0,
                }
        return {
            "hosts": hosts,
            "alive": sorted(alive),
            "tenants": self.placement(),
            "stale_after_s": float(stale_after_s),
        }

    def fleet_snapshot(self, endpoint: str) -> Dict[str, Any]:
        """The accumulated registry snapshot for one host (exact fold of
        every delta received so far, ``Registry.snapshot()`` shape)."""
        with self._fleet_lock:
            rec = self._fleet.get(endpoint)
            if rec is None:
                raise ValueError(
                    f"no obs stream state for endpoint {endpoint!r}."
                )
            return rec["acc"].snapshot()

    def fleet_chrome_trace(self, **json_kwargs: Any) -> str:
        """One Chrome/Perfetto trace for the whole fleet: every host's
        pushed timeline events merged into the router's own timeline via
        ``obs.chrome_trace(extra_events=)``, with ``pid`` = the host
        endpoint — each host renders as its own process row, tenant spans
        nested under it. Open in ``chrome://tracing`` / Perfetto."""
        from torcheval_tpu.obs import chrome_trace

        extra: List[Dict[str, Any]] = []
        with self._fleet_lock:
            for ep, rec in self._fleet.items():
                for e in rec["events"]:
                    tagged = dict(e)
                    tagged["rank"] = ep  # pid=host in the merged trace
                    extra.append(tagged)
        return chrome_trace(extra_events=extra, **json_kwargs)

    # ------------------------------------------------------------ migration
    def _wait_not_migrating(
        self, endpoint: str, *, timeout_s: float = 300.0
    ) -> None:
        """Block until no migration is in flight for ``endpoint`` (or the
        bound expires), so a caller that returns afterwards observes
        post-migration routing."""
        with self._cv:
            self._cv.wait_for(
                lambda: endpoint not in self._migrating, timeout=timeout_s
            )

    def _host_failed(self, endpoint: str, *, cause: BaseException) -> None:
        """Mark ``endpoint`` dead and migrate every tenant it held.
        Single-flight per endpoint: exactly one thread runs the
        migration; concurrent reporters of the same failure WAIT for it
        (their booked batches are delivered by the migration's replay,
        so returning before it finishes would lie to them). The network
        work runs OUTSIDE the router lock — healthy hosts keep serving
        while a dead one is migrated."""
        with self._cv:
            if endpoint in self._alive:
                self._alive.discard(endpoint)
                self._migrating.add(endpoint)
            elif endpoint in self._migrating:
                self._cv.wait_for(
                    lambda: endpoint not in self._migrating, timeout=300.0
                )
                return
            else:
                return  # already dead and fully migrated
        _logger.warning(
            "router: endpoint %s marked dead (%s); migrating its tenants.",
            endpoint,
            cause,
        )
        try:
            self._migrate_host(endpoint, reason="host_failure")
        finally:
            with self._cv:
                self._migrating.discard(endpoint)
                self._cv.notify_all()

    def drain(
        self, endpoint: str, *, timeout_s: Any = None
    ) -> Dict[str, Any]:
        """Gracefully move every tenant off ``endpoint``: the host
        checkpoints-and-evicts them all (admissions stop immediately),
        the endpoint leaves the alive set, and the tenants re-attach
        elsewhere from their fresh checkpoints. Returns
        ``{"drained": {tenant: ckpt_path}, "migrated": [tenant, ...]}``."""
        if endpoint not in self._clients:
            raise ValueError(f"unknown endpoint {endpoint!r}.")
        kw = {} if timeout_s is None else {"timeout_s": timeout_s}
        drained = self._clients[endpoint].drain(**kw)
        with self._cv:
            if endpoint in self._migrating:
                # a concurrent failure migration beat us to the move;
                # wait it out — the drain still checkpointed everything
                self._cv.wait_for(
                    lambda: endpoint not in self._migrating, timeout=300.0
                )
                return {"drained": drained, "migrated": []}
            self._alive.discard(endpoint)
            self._migrating.add(endpoint)
        try:
            migrated = self._migrate_host(endpoint, reason="drain")
        finally:
            with self._cv:
                self._migrating.discard(endpoint)
                self._cv.notify_all()
        return {"drained": drained, "migrated": migrated}

    def _migrate_host(self, endpoint: str, *, reason: str) -> List[str]:
        """Move every tenant routed to ``endpoint`` onto survivors.
        Caller holds the endpoint's ``_migrating`` slot (single-flight),
        NOT the router lock — the per-tenant network work must not stall
        ops against healthy hosts."""
        with self._lock:
            victims = [
                t
                for t, rec in self._tenants.items()
                if rec.endpoint == endpoint
            ]
        migrated: List[str] = []
        with _obs.span(
            "serve.router.migrate", endpoint=endpoint, reason=reason
        ):
            for tenant_id in victims:
                try:
                    self._migrate_tenant(tenant_id, endpoint, reason)
                    migrated.append(tenant_id)
                except Exception as e:  # noqa: BLE001 - containment wall
                    # a tenant that cannot migrate (no usable checkpoint —
                    # incl. a remote CheckpointError — no survivors, a
                    # checkpoint_behind refusal) is dropped from the
                    # routing table with a loud log, and the REST of the
                    # host's tenants still migrate: one tenant's bad
                    # checkpoint must never strand its neighbors on a
                    # dead endpoint. The caller's next op on the dropped
                    # tenant raises unknown_tenant, never a silent ghost.
                    _logger.error(
                        "router: tenant %r failed to migrate off %s: %s",
                        tenant_id,
                        endpoint,
                        e,
                    )
                    with self._lock:
                        self._tenants.pop(tenant_id, None)
        if _obs._enabled and victims:
            _trace.instant(
                "serve.router.migrated",
                kind="serve",
                endpoint=endpoint,
                reason=reason,
                tenants=len(migrated),
            )
        return migrated

    def _migrate_tenant(
        self, tenant_id: str, from_ep: str, reason: str
    ) -> None:
        with self._lock:
            rec = self._tenants.get(tenant_id)
        if rec is None:
            return  # detached while the migration was queued
        exported = self._clients[from_ep].export_tenant(tenant_id)
        new_ep = self._place(tenant_id)
        client = self._clients[new_ep]
        knobs = dict(rec.knobs)
        knobs["resume"] = "auto"  # restore the shared-root checkpoint
        attach_resp = client.attach(tenant_id, rec.spec, **knobs)
        replayed = client.adopt_tenant(
            tenant_id, exported, restored_seq=int(attach_resp["last_seq"])
        )
        with self._lock:
            rec.endpoint = new_ep
        if _obs._enabled:
            _obs.counter("serve.router.migrations", reason=reason)
        _logger.warning(
            "router: migrated tenant %r %s -> %s (%s; checkpoint seq %d, "
            "replayed %d)",
            tenant_id,
            from_ep,
            new_ep,
            reason,
            int(attach_resp["last_seq"]),
            replayed,
        )

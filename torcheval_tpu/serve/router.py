"""`EvalRouter`: tenant placement, health probing, and cross-host migration.

The cluster layer of ISSUE 10. A router fronts N eval-service hosts (each
an :class:`EvalServer` + :class:`EvalDaemon` pair sharing one checkpoint
root) with one :class:`~torcheval_tpu.serve.EvalClient` per endpoint, and
makes the death of any single host a routine event (the TPU-serving
stance: host loss and draining are absorbed, not outages):

* **placement** — tenants place by rendezvous (highest-random-weight)
  hashing of ``tenant_id`` over the *alive* endpoint set: deterministic,
  coordination-free, and minimal-movement (a host's death moves only its
  own tenants, never reshuffles survivors);
* **health probing** — ``health()`` probes every alive host's
  ``daemon.health()`` over the wire; a probe failure (or any transport
  failure on a tenant op) marks the host dead and triggers migration;
* **failure migration** — a dead host's tenants re-``attach`` on a
  surviving host with ``resume="auto"``: the daemon restores each
  tenant's latest checkpoint from the shared root (``resilience.save``'s
  contract is location-independent — evict-on-idle and flushes already
  write there) and re-arms its dedup watermark from the checkpoint
  manifest; the router then replays the client-side replay buffer's
  un-durable tail. Acked-and-checkpointed batches come back through the
  checkpoint, un-acked ones through replay, and seq dedup absorbs the
  overlap — post-migration computes match a fault-free oracle
  bit-identically;
* **graceful drain** — ``drain(endpoint)`` asks the host to
  checkpoint-and-evict every tenant (it stops admitting immediately),
  then migrates them the same way; use it before planned maintenance so
  the "un-acked tail" is empty and the blackout is one restore long.

Transport knobs ride through ``**client_kwargs`` to every per-host
client: ``pipeline_depth=`` turns on ISSUE 18's deferred-ack submit
pipelining against hosts that grant it (a migrated tenant's replay
drains through the ordinary lock-step path first, then new submits
pipeline to the survivor), and ``local_transport=False`` forces TCP
even when a fronted server shares this process (the bench's migration
leg pins it off so the blackout measured is the wire's).

Observability: ``serve.router.migrations{reason=}``,
``serve.router.replays{tenant=}`` (counted at the replaying client),
``serve.router.probe_failures{endpoint=}``, plus a
``serve.router.migrate`` span per migrated host (a migration-blackout
bar in the Chrome trace).

Fleet telemetry (ISSUE 16): ``subscribe_obs()`` opens one obs push
stream per alive host (``EvalClient.subscribe_obs`` — delta snapshots +
``load_report`` on the server's timer, degrading to ``health()`` polling
against old peers); the router folds each host's deltas into a
:class:`~torcheval_tpu.obs.DeltaAccumulator` and keeps its latest load
report. ``fleet_status()`` serves the folded view with staleness marking
(a host whose last push is older than ``stale_after_s`` — default three
push intervals — is ``stale`` BEFORE the failure detector evicts it);
``fleet_chrome_trace()`` merges every host's pushed timeline events into
one Chrome trace, pid per host. None of it adds collective rounds: the
stream rides the serve wire, not the toolkit funnel.

Elastic fleet (ISSUE 19) — the Podracer stance: the fleet grows, shrinks
and rebalances under load instead of capping throughput at one hot host:

* **load-aware placement** — ``_place`` is *weighted* rendezvous: each
  alive endpoint's rendezvous draw is scored ``-w / ln(u)`` (highest
  score wins) where ``u`` is the tenant-endpoint hash mapped into (0,1)
  and the weight ``w`` folds that host's latest fresh ``load_report``
  (queue utilization, tenant-slot utilization, submit p99/EWMA against
  ``latency_target_s``, optional HBM budget). With no load signal every
  weight is 1 and the argmax is EXACTLY the classic unweighted
  rendezvous (a monotone transform of the same draw), so placement
  stays deterministic and minimal-movement; hosts whose fresh report
  says ``draining`` — or whose subscribed stream went silent past the
  staleness horizon — are ineligible for NEW tenants;
* **rebalancing** — ``rebalance()`` (one pass; ``start_rebalancer()``
  runs it on a timer) migrates tenants off hot hosts through the SAME
  checkpoint+replay machinery as failure migration, made loss-proof for
  a live source: flush (durable resume point) → ``export_tenant`` (wire
  state + booked tail carried off; racing submits absorb through the
  reroute-grace window) → ``drop_tenant`` on the source → re-attach
  ``resume="auto"`` + ``adopt_tenant`` on the target. Hysteresis knobs
  (``hot_load`` threshold, minimum ``improvement`` gap, per-tenant
  ``min_dwell_s``, ``max_moves`` per pass) bound movement so the fleet
  provably never thrashes;
* **hot-tenant splitting** — ``split_tenant(tid, n)`` shards one
  tenant's stream across N replica tenants (``tid``, ``tid@r1``, …),
  each a first-class routed tenant with its OWN seq namespace (the
  replica id IS the dedup key, so exactly-once holds per replica and
  failover/migration work per-replica unchanged). ``submit`` fans out
  by a stable hash of the split ordinal; ``compute`` flushes every
  replica, rebuilds each collection through the daemon's own
  ``build_collection`` path, restores the flush checkpoints, and merges
  — ``merge_collections`` for sliced tenants (cohorts re-keyed by
  original id), per-member ``merge_state`` otherwise — bit-identical to
  the single-stream oracle;
* **autoscale hooks** — ``add_host()`` / ``remove_host()`` (= drain +
  forget) at runtime, and ``autoscale_step(policy)`` drives a pluggable
  :class:`ScalingPolicy` from ``fleet_status()``'s aggregate
  ``headroom`` scalar, so a bench-driven simulator or an external
  orchestrator grows the fleet under load.

New instruments: ``serve.router.rebalances{endpoint=}`` (one per
completed rebalance move, alongside
``serve.router.migrations{reason=rebalance}``),
``serve.router.splits{tenant=}``, and the ``serve.fleet.headroom``
gauge recorded by ``fleet_status()``.

Durable control plane (ISSUE 20): with ``journal_dir=`` every
control-plane mutation — placement, migration move, split, drain, host
add/remove — appends one fsync'd record to a
:class:`~torcheval_tpu.serve.journal.RouterJournal` before the call
returns (submits never touch it; seq watermarks are the hosts' to
keep). A new router constructed over the same ``journal_dir`` replays
the journal and then **reconciles** against the live fleet via the
``list_tenants`` wire op: journaled tenants still attached are
*adopted* in place (client seq state re-seeded from the host's
``last_seq`` — zero blackout beyond the probe), tenants whose host died
while the router was down are *re-placed* through the ordinary
``attach(resume="auto")`` checkpoint machinery, live tenants the
journal never heard of are *orphan-adopted* from the attach-time
spec/knobs each server records, a tenant found attached on TWO hosts
(killed mid-migration) keeps the copy that advanced further and the
stale one is dropped without a checkpoint, and split fan-out namespaces
are reconstructed exactly — the fan-out ordinal is the sum of replica
``last_seq``\\ s, because every parent submit bumps exactly one
replica's seq by one. Outcomes count into
``serve.router.recoveries{outcome=}`` and the whole pass is summarized
in :attr:`EvalRouter.last_recovery` (the drill's blackout artifact).
"""

from __future__ import annotations

import hashlib
import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.resilience import chaos as _chaos
from torcheval_tpu.serve.client import EvalClient
from torcheval_tpu.serve.errors import AdmissionError, ServeError, WireError
from torcheval_tpu.serve.journal import RouterJournal

_logger = logging.getLogger(__name__)

__all__ = ["EvalRouter", "HeadroomScalingPolicy", "ScalingPolicy"]


def _replica_id(tenant_id: str, k: int) -> str:
    """Replica ``k``'s tenant id. Replica 0 IS the original tenant (its
    id, state, and checkpoint lineage are unchanged by a split); higher
    replicas get a namespaced id, which makes the replica id part of the
    wire dedup key for free — each replica runs its own monotonic seq."""
    return tenant_id if k == 0 else f"{tenant_id}@r{k}"


class _RoutedTenant:
    __slots__ = (
        "spec",
        "knobs",
        "endpoint",
        "placed_at",
        "replicas",
        "parent",
        "split_next",
    )

    def __init__(
        self,
        spec: Any,
        knobs: Dict[str, Any],
        endpoint: str,
        *,
        parent: Optional[str] = None,
    ):
        self.spec = spec
        self.knobs = knobs
        self.endpoint = endpoint
        self.placed_at = time.monotonic()  # rebalance dwell clock
        self.replicas: Optional[List[str]] = None  # split parent only
        self.parent = parent  # set on replicas k >= 1
        self.split_next = 0  # fan-out ordinal (split parent only)


class EvalRouter:
    """Route tenants across eval-service hosts; survive any one of them.

    ``endpoints`` are ``"host:port"`` strings (or ``(host, port)``
    tuples); ``client_kwargs`` configure every per-host
    :class:`EvalClient` (deadlines, breaker, replay capacity — all
    validated there). The hosts must share one checkpoint root (each
    daemon's ``evict_dir``) for migration to have a resume source.

    Thread-safe for the many-producers shape: submits for different
    tenants proceed concurrently (per-tenant client locks); migration
    holds the router lock so a failing host is migrated exactly once.
    """

    def __init__(
        self,
        endpoints: Sequence[Any],
        *,
        client_factory: Any = EvalClient,
        reroute_grace_s: float = 60.0,
        probe_timeout_s: Optional[float] = 5.0,
        latency_target_s: float = 1.0,
        hbm_budget_bytes: Optional[int] = None,
        journal_dir: Optional[str] = None,
        **client_kwargs: Any,
    ) -> None:
        if not endpoints:
            raise ValueError("EvalRouter needs at least one endpoint.")
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        for knob, value in (
            ("reroute_grace_s", reroute_grace_s),
            ("probe_timeout_s", probe_timeout_s),
            ("latency_target_s", latency_target_s),
        ):
            try:
                _check_timeout_s(value)
            except ValueError as e:
                raise ValueError(f"{knob}: {e}") from None
        if reroute_grace_s is None:
            raise ValueError("reroute_grace_s must be a positive number.")
        if latency_target_s is None:
            raise ValueError("latency_target_s must be a positive number.")
        if hbm_budget_bytes is not None and (
            not isinstance(hbm_budget_bytes, int) or hbm_budget_bytes < 1
        ):
            raise ValueError(
                f"hbm_budget_bytes must be a positive int or None, got "
                f"{hbm_budget_bytes!r}."
            )
        self._reroute_grace_s = float(reroute_grace_s)
        self._probe_timeout_s = probe_timeout_s
        # load-score knobs (ISSUE 19): submit p99 at/above the latency
        # target reads as full pressure; HBM pressure participates only
        # when a budget is declared
        self._latency_target_s = float(latency_target_s)
        self._hbm_budget_bytes = hbm_budget_bytes
        # kept so add_host() can mint new per-host clients at runtime
        # with the exact construction the initial endpoints got
        self._client_factory = client_factory
        self._client_kwargs = dict(client_kwargs)
        self._clients: Dict[str, EvalClient] = {}
        for ep in endpoints:
            client = client_factory(ep, **client_kwargs)
            self._clients[client.endpoint] = client
        if len(self._clients) != len(endpoints):
            raise ValueError(f"duplicate endpoints in {endpoints!r}.")
        self._alive = set(self._clients)
        self._tenants: Dict[str, _RoutedTenant] = {}
        self._lock = threading.RLock()
        # endpoints whose migration is in flight: the lock guards only
        # the routing tables; migration's network work (attach + restore
        # + replay per tenant) runs OUTSIDE it so one dying host never
        # stalls traffic to healthy hosts. _cv wakes threads waiting for
        # an in-flight migration to finish.
        self._cv = threading.Condition(self._lock)
        self._migrating: set = set()
        # fleet telemetry (ISSUE 16): per-endpoint folded obs state,
        # guarded by its own lock — push callbacks run on subscriber
        # threads and must never contend with migration's router lock
        self._fleet_lock = threading.Lock()
        self._obs_subs: Dict[str, Any] = {}
        self._fleet: Dict[str, Dict[str, Any]] = {}
        self._obs_interval_s: Optional[float] = None
        self._stale_after_s: Optional[float] = None
        self._fleet_max_events = 4096
        # background rebalancer (ISSUE 19)
        self._rebalance_thread: Optional[threading.Thread] = None
        self._rebalance_stop = threading.Event()
        # durable control plane (ISSUE 20): endpoints taken out of the
        # alive set by an explicit drain stay out across a recovery (a
        # DEAD endpoint, by contrast, is re-derived by probing — the
        # journal records intent, the fleet records reality)
        self._drained: set = set()
        self._journal: Optional[RouterJournal] = None
        # the last recovery pass's summary (outcomes, duration, fleet),
        # None for a journal-less or genuinely cold start
        self.last_recovery: Optional[Dict[str, Any]] = None
        if journal_dir is not None:
            self._journal = RouterJournal(
                journal_dir, snapshot_fn=self._journal_state
            )
            self._recover()

    # -------------------------------------------------------------- journal
    def _journal_append(self, kind: str, **fields: Any) -> None:
        """Durably record one control-plane mutation. A journal write
        failure (disk full, dir removed) is logged, never raised — the
        fleet keeps serving and the gap heals at the next recovery's
        reconciliation pass (orphan adoption covers unjournaled
        placements)."""
        if self._journal is None:
            return
        try:
            self._journal.append(kind, **fields)
        except (OSError, ValueError, TypeError) as e:
            _logger.error(
                "router: journal append (%s) failed: %s — continuing "
                "unjournaled; the next recovery reconciles the gap.",
                kind,
                e,
            )

    def _journal_state(self) -> Dict[str, Any]:
        """The full routing table as one compactable snapshot."""
        with self._lock:
            return {
                "tenants": {
                    tid: {
                        "endpoint": rec.endpoint,
                        "spec": rec.spec,
                        "knobs": rec.knobs,
                        "parent": rec.parent,
                        "replicas": rec.replicas,
                    }
                    for tid, rec in self._tenants.items()
                },
                "endpoints": sorted(self._clients),
                "drained": sorted(self._drained),
            }

    def _recover(self) -> None:
        """Rebuild the routing table from the journal, then reconcile it
        against the live fleet (module docstring: adopt / re-place /
        orphan-adopt / drop, split reconstruction). Runs once, from the
        constructor, before the router serves anything — the wall-clock
        of this method IS the control-plane blackout."""
        t0 = time.monotonic()
        snapshot, records = self._journal.replay()
        expected: Dict[str, Dict[str, Any]] = {}
        known_eps = set(self._clients)
        drained: set = set()
        if snapshot:
            for tid, meta in (snapshot.get("tenants") or {}).items():
                expected[tid] = dict(meta)
            known_eps |= set(snapshot.get("endpoints") or ())
            drained |= set(snapshot.get("drained") or ())
        for r in records:
            kind = r.get("kind")
            if kind == "place":
                expected[r["tenant"]] = {
                    "endpoint": r.get("endpoint"),
                    "spec": r.get("spec"),
                    "knobs": r.get("knobs") or {},
                    "parent": r.get("parent"),
                    "replicas": None,
                }
            elif kind == "remove":
                expected.pop(r.get("tenant"), None)
            elif kind == "move":
                meta = expected.get(r.get("tenant"))
                if meta is not None:
                    meta["endpoint"] = r.get("endpoint")
            elif kind == "split":
                meta = expected.get(r.get("tenant"))
                if meta is not None:
                    meta["replicas"] = list(r.get("replicas") or ())
            elif kind == "host_add":
                known_eps.add(r.get("endpoint"))
                drained.discard(r.get("endpoint"))
            elif kind == "host_remove":
                known_eps.discard(r.get("endpoint"))
                drained.discard(r.get("endpoint"))
            elif kind == "host_drain":
                drained.add(r.get("endpoint"))
            # unknown kinds: a newer writer's record — skip, never crash
        # endpoints the journal knows that the constructor was not given
        # (hosts added at runtime before the crash) get clients minted
        # with the same factory/kwargs
        for ep in sorted(e for e in known_eps if e and e not in self._clients):
            try:
                client = self._client_factory(ep, **self._client_kwargs)
            except (ValueError, OSError) as e:
                _logger.warning(
                    "router recovery: cannot mint a client for journaled "
                    "endpoint %s: %s", ep, e,
                )
                continue
            self._clients[client.endpoint] = client
        # probe: aliveness comes from the fleet, not the journal — a
        # host that died AND restarted while the router was down is
        # simply alive again; only an explicit drain survives recovery
        self._drained = drained & set(self._clients)
        alive: set = set()
        live: Dict[str, Dict[str, Any]] = {}
        stale_copies: List[Any] = []
        for ep in sorted(self._clients):
            if ep in self._drained:
                continue
            try:
                tenants = self._clients[ep].list_tenants(
                    timeout_s=self._probe_timeout_s, attempts=1
                )
            except (WireError, ServeError) as e:
                if _obs._enabled:
                    _obs.counter(
                        "serve.router.probe_failures", endpoint=ep
                    )
                _logger.warning(
                    "router recovery: endpoint %s did not answer the "
                    "reconciliation probe (%s); its tenants re-place "
                    "from checkpoints.", ep, e,
                )
                continue
            alive.add(ep)
            for tid, info in tenants.items():
                cur = dict(info or {})
                cur["endpoint"] = ep
                prior = live.get(tid)
                if prior is None:
                    live[tid] = cur
                    continue
                # attached on TWO hosts: a migration was mid-flight when
                # the router died. Keep the copy that advanced further;
                # the stale one is dropped WITHOUT a checkpoint so it
                # cannot publish a zombie generation.
                keep, stale = (
                    (cur, prior)
                    if int(cur.get("last_seq") or 0)
                    >= int(prior.get("last_seq") or 0)
                    else (prior, cur)
                )
                live[tid] = keep
                stale_copies.append((tid, stale["endpoint"]))
        self._alive = alive
        outcomes: Dict[str, int] = {}

        def _count(outcome: str) -> None:
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
            if _obs._enabled:
                _obs.counter("serve.router.recoveries", outcome=outcome)

        for tid, ep in stale_copies:
            try:
                self._clients[ep].drop_tenant(tid, checkpoint=False)
            except (ServeError, WireError) as e:
                _logger.warning(
                    "router recovery: stale copy of %r on %s did not "
                    "release cleanly: %s", tid, ep, e,
                )
            _count("stale_dropped")
        # torn-split rollback: a replica whose parent never committed a
        # split record is the debris of a mid-split crash — the split
        # itself is atomic, so the replica is detached, matching the
        # crash-free rollback path of split_tenant
        for tid in sorted(expected):
            meta = expected[tid]
            parent = meta.get("parent")
            if not parent:
                continue
            pmeta = expected.get(parent)
            committed = bool(
                pmeta
                and pmeta.get("replicas")
                and tid in pmeta["replicas"]
            )
            if committed:
                continue
            expected.pop(tid)
            info = live.pop(tid, None)
            if info is not None:
                try:
                    self._clients[info["endpoint"]].drop_tenant(
                        tid, checkpoint=False
                    )
                except (ServeError, WireError):
                    pass
            _count("split_rolled_back")
        seqs: Dict[str, int] = {}
        for tid in sorted(expected):
            meta = expected[tid]
            knobs = dict(meta.get("knobs") or {})
            info = live.pop(tid, None)
            if info is not None:
                # still attached where (or wherever) the fleet holds it:
                # adopt in place, re-seeding this router's client-side
                # seq cursor from the host's watermark
                rec = _RoutedTenant(
                    meta.get("spec"),
                    knobs,
                    info["endpoint"],
                    parent=meta.get("parent"),
                )
                self._tenants[tid] = rec
                seqs[tid] = int(info.get("last_seq") or 0)
                self._clients[info["endpoint"]].adopt_attached(
                    tid, seqs[tid]
                )
                _count("adopted")
                continue
            # its host died while the router was down: re-place from the
            # shared checkpoint root. The replay buffer died with the
            # old router, so the resume point is the last DURABLE
            # watermark — producers resubmit above it, dedup absorbs
            # any overlap.
            place_knobs = dict(knobs)
            place_knobs["resume"] = "auto"
            try:
                ep = self._attach_anywhere(
                    tid, meta.get("spec"), place_knobs
                )
            except (ServeError, WireError, AdmissionError) as e:
                _logger.error(
                    "router recovery: journaled tenant %r could not be "
                    "re-placed (%s); dropping it from the routing "
                    "table.", tid, e,
                )
                _count("dropped")
                continue
            self._tenants[tid] = _RoutedTenant(
                meta.get("spec"), knobs, ep, parent=meta.get("parent")
            )
            # the freshly attached client state carries the restored
            # watermark — read it back for split reconstruction
            state = self._clients[ep]._tenants.get(tid)
            seqs[tid] = int(state.durable_seq) if state is not None else 0
            _count("replaced")
        # orphans: live tenants the journal never heard of (attached in
        # the crash window before their journal record landed, or placed
        # behind the router's back). Adoptable only when the host
        # recorded the attach-time spec; an old host's degraded
        # list_tenants has none, so the tenant stays unrouted — loudly.
        for tid in sorted(live):
            info = live[tid]
            if info.get("spec") is None:
                _logger.warning(
                    "router recovery: live tenant %r on %s carries no "
                    "attach spec (old host?); leaving it unrouted.",
                    tid, info["endpoint"],
                )
                _count("orphan_skipped")
                continue
            self._tenants[tid] = _RoutedTenant(
                info["spec"],
                dict(info.get("knobs") or {}),
                info["endpoint"],
            )
            seqs[tid] = int(info.get("last_seq") or 0)
            self._clients[info["endpoint"]].adopt_attached(
                tid, seqs[tid]
            )
            _count("orphan_adopted")
        # split reconstruction: surviving replicas re-form the fan-out
        # set, and the fan-out ordinal is reconciliation-derived — every
        # parent submit bumped exactly one replica's seq by one, so the
        # ordinal is the sum of replica watermarks, exactly
        for tid, meta in expected.items():
            replicas = meta.get("replicas")
            rec = self._tenants.get(tid)
            if not replicas or rec is None:
                continue
            present = [r for r in replicas if r in self._tenants]
            rec.replicas = present if len(present) >= 2 else None
            rec.split_next = sum(seqs.get(r, 0) for r in present)
        duration_s = time.monotonic() - t0
        self.last_recovery = {
            "outcomes": outcomes,
            "duration_s": duration_s,
            "alive": sorted(alive),
            "drained": sorted(self._drained),
            "tenants": len(self._tenants),
            "journal_records": len(records),
        }
        if _obs._enabled:
            _trace.instant(
                "serve.router.recovered",
                kind="router",
                duration_s=duration_s,
                tenants=len(self._tenants),
            )
        _logger.info(
            "router: recovered from journal in %.3fs — %s (alive: %s).",
            duration_s,
            outcomes or "cold start",
            sorted(alive),
        )
        # fold the reconciled table into one snapshot so the next
        # recovery replays the OUTCOME, not the pre-crash history
        try:
            self._journal.compact(self._journal_state())
        except (OSError, ValueError) as e:
            _logger.error(
                "router: post-recovery journal compaction failed: %s", e
            )

    # ------------------------------------------------------------ placement
    def _host_load(self, report: Optional[Dict[str, Any]]) -> float:
        """Fold one schema-1 ``load_report`` into a scalar load in
        [0, 0.999]: the max of queue utilization, tenant-slot
        utilization, submit latency pressure (p99, else EWMA, against
        ``latency_target_s``), and — when ``hbm_budget_bytes`` is set —
        HBM pressure. Max (not mean): placement must route around the
        binding constraint, whichever it is."""
        if not report:
            return 0.0
        pressures = [0.0]
        queue = report.get("queue") or {}
        qcap = queue.get("capacity") or 0
        if qcap:
            pressures.append(
                float(queue.get("depth", 0)) / float(qcap)
            )
        capacity = report.get("capacity") or {}
        max_t = capacity.get("max_tenants") or 0
        if max_t:
            pressures.append(
                float(capacity.get("active_tenants", 0)) / float(max_t)
            )
        latency = report.get("latency") or {}
        p99 = (
            latency.get("submit_p99_s")
            or latency.get("submit_ewma_s")
            or 0.0
        )
        pressures.append(float(p99) / self._latency_target_s)
        if self._hbm_budget_bytes:
            hbm = report.get("hbm") or {}
            pressures.append(
                float(hbm.get("bytes_sum", 0.0))
                / float(self._hbm_budget_bytes)
            )
        return min(0.999, max(0.0, max(pressures)))

    def _fleet_loads(self) -> Dict[str, Dict[str, Any]]:
        """Per-alive-endpoint load view from the folded fleet state:
        ``{ep: {"load": float|None, "draining": bool, "suspect":
        bool}}``. Only a FRESH report (inside the staleness horizon)
        contributes ``load`` and ``draining`` — a stale number must not
        weight placement. ``suspect`` marks a host whose subscribed
        stream delivered at least once and then went quiet past the
        horizon: ineligible for new tenants until the failure detector
        rules (a host never heard from carries no signal and stays
        eligible — no signal is not bad signal)."""
        horizon = self._stale_after_s if self._stale_after_s else 3.0
        now = time.monotonic()
        out: Dict[str, Dict[str, Any]] = {}
        alive = self.alive
        with self._fleet_lock:
            for ep in alive:
                rec = self._fleet.get(ep)
                subscribed = ep in self._obs_subs
                report = rec["report"] if rec else None
                age = (
                    now - rec["received_at"]
                    if rec is not None and rec["received_at"]
                    else None
                )
                fresh = age is not None and age <= horizon
                out[ep] = {
                    "load": (
                        self._host_load(report)
                        if fresh and report is not None
                        else None
                    ),
                    "draining": bool(
                        fresh and report and report.get("draining")
                    ),
                    "suspect": bool(
                        subscribed and age is not None and not fresh
                    ),
                }
        return out

    def _place(self, tenant_id: str, *, exclude: Any = ()) -> str:
        """Weighted rendezvous placement over the alive set: every
        endpoint's hash draw ``u`` is scored ``-w / ln(u)`` and the
        highest score wins, with weight ``w = 1 - load`` folded from the
        host's latest fresh ``load_report``. With no load signal every
        weight is 1 and the argmax is EXACTLY classic
        highest-random-weight hashing (monotone transform of the same
        draw) — deterministic for a given alive set, minimal-movement
        when hosts die. Hosts whose fresh report says ``draining``, or
        whose subscribed stream went silent past the staleness horizon,
        are ineligible for NEW tenants (unless that would empty the
        candidate set — a merely-quiet fleet must still place)."""
        with self._lock:
            alive = sorted(self._alive)
        if exclude:
            alive = [ep for ep in alive if ep not in exclude]
        if not alive:
            raise ServeError(
                "no_hosts", "every endpoint is dead or drained."
            )
        info = self._fleet_loads()
        eligible = [
            ep
            for ep in alive
            if ep not in info
            or not (info[ep]["draining"] or info[ep]["suspect"])
        ] or alive
        best, best_score = None, -math.inf
        for ep in eligible:
            load = info.get(ep, {}).get("load")
            weight = max(1e-3, 1.0 - (load or 0.0))
            digest = hashlib.sha256(
                f"{tenant_id}@{ep}".encode()
            ).digest()
            # first 8 digest bytes -> u in (0,1); ln(u) < 0, so the
            # score is positive and monotone in u at equal weights
            u = (int.from_bytes(digest[:8], "big") + 0.5) / 2.0**64
            score = -weight / math.log(u)
            if score > best_score:
                best, best_score = ep, score
        return best

    @property
    def endpoints(self) -> List[str]:
        return sorted(self._clients)

    @property
    def alive(self) -> List[str]:
        with self._lock:
            return sorted(self._alive)

    def placement(self) -> Dict[str, str]:
        """Current ``{tenant_id: endpoint}`` map."""
        with self._lock:
            return {t: rec.endpoint for t, rec in self._tenants.items()}

    def close(self) -> None:
        self.stop_rebalancer()  # before the clients its moves would use
        self.unsubscribe_obs()
        for client in self._clients.values():
            client.close()
        if self._journal is not None:
            self._journal.close()

    def __enter__(self) -> "EvalRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ tenant api
    def attach(
        self, tenant_id: str, spec: Dict[str, Any], **knobs: Any
    ) -> str:
        """Place and attach one tenant; returns the chosen endpoint.
        ``spec``/``knobs`` are recorded so a migration can re-attach the
        tenant identically elsewhere."""
        with self._lock:
            if tenant_id in self._tenants:
                raise ServeError(
                    "duplicate_tenant",
                    f"tenant {tenant_id!r} is already routed.",
                )
        ep = self._attach_anywhere(tenant_id, spec, knobs)
        with self._lock:
            self._tenants[tenant_id] = _RoutedTenant(spec, dict(knobs), ep)
        # journaled AFTER the commit: a crash in between leaves a live,
        # unjournaled tenant — exactly what recovery's orphan adoption
        # reconciles (journaling first would instead fabricate a tenant
        # the caller was never told about)
        self._journal_append(
            "place",
            tenant=tenant_id,
            endpoint=ep,
            spec=spec,
            knobs=dict(knobs),
            parent=None,
        )
        return ep

    def _attach_anywhere(
        self,
        tenant_id: str,
        spec: Dict[str, Any],
        knobs: Dict[str, Any],
        *,
        exclude: Any = (),
    ) -> str:
        """Place-and-attach with dead/draining-host absorption; returns
        the endpoint that admitted the tenant. Does NOT touch the
        routing table — callers record the placement."""
        while True:
            ep = self._place(tenant_id, exclude=exclude)
            try:
                self._clients[ep].attach(tenant_id, spec, **knobs)
            except WireError as e:
                if not e.retryable:
                    raise
                self._host_failed(ep, cause=e)
                continue
            except AdmissionError as e:
                if e.reason != "draining":
                    raise
                # the rendezvous pick is mid-decommission: treat it like
                # a failed host (same single-flight migration machinery;
                # if the router's own drain() already owns the move this
                # just waits for it) and re-place among the survivors
                self._host_failed(ep, cause=e)
                continue
            return ep

    def _routed(self, tenant_id: str) -> _RoutedTenant:
        with self._lock:
            rec = self._tenants.get(tenant_id)
        if rec is None:
            raise ServeError(
                "unknown_tenant",
                f"tenant {tenant_id!r} is not routed; attach it first.",
            )
        return rec

    def _with_failover(self, tenant_id: str, op) -> Any:
        """Run one tenant op against its current host; on a transport
        failure, migrate the host's tenants and run the op once more on
        the new placement (compute/flush/detach are idempotent). A second
        transport failure surfaces. The in-flight-migration window
        (``tenant_migrated`` / client-side ``unknown_tenant`` for a
        still-routed tenant) re-routes within ``reroute_grace_s``, like
        ``submit``."""
        wire_failures = 0
        deadline = time.monotonic() + self._reroute_grace_s
        sleep_s = 0.02
        while True:
            rec = self._routed(tenant_id)
            client = self._clients[rec.endpoint]
            try:
                return op(client)
            except WireError as e:
                wire_failures += 1
                if wire_failures >= 2 or not e.retryable:
                    # a protocol error (version skew) is not evidence the
                    # HOST is dead — don't let it trigger a migration
                    raise
                self._host_failed(rec.endpoint, cause=e)
            except ServeError as e:
                if e.reason == "tenant_migrated" or (
                    e.reason == "unknown_tenant"
                    and tenant_id in self._tenants
                ):
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(sleep_s)
                    sleep_s = min(sleep_s * 2, 0.5)
                    continue
                raise

    def submit(self, tenant_id: str, *args: Any, **kw: Any) -> bool:
        """Deliver one batch; a split tenant fans out by stable hash.

        For an unsplit tenant this is :meth:`_submit_one` directly. For a
        split tenant, a monotone per-tenant ordinal is hashed to pick the
        replica, so the fan-out is deterministic given arrival order and
        any retry of THIS batch stays on the replica that booked its seq
        (exactly-once holds per replica namespace)."""
        if _chaos.router_armed():
            _chaos.on_router_op("submit", tenant_id)
        rec = self._routed(tenant_id)
        with self._lock:
            replicas = list(rec.replicas) if rec.replicas else None
            if replicas:
                ordinal = rec.split_next
                rec.split_next = ordinal + 1
        if not replicas:
            return self._submit_one(tenant_id, *args, **kw)
        digest = hashlib.sha256(f"{tenant_id}#{ordinal}".encode()).digest()
        target = replicas[int.from_bytes(digest[:8], "big") % len(replicas)]
        return self._submit_one(target, *args, **kw)

    def _submit_one(self, tenant_id: str, *args: Any, **kw: Any) -> bool:
        """Deliver one batch, surviving a host death or drain mid-submit.

        A transport-failed submit whose batch was already booked in the
        client replay buffer is delivered BY the migration's replay —
        resubmitting it here under a fresh seq would apply it twice, so
        failover only resubmits when the failure struck before booking.
        Three structured rejects mean "the placement is changing, the
        batch was NOT booked; wait and re-route" and are absorbed up to
        ``reroute_grace_s``: ``tenant_migrated`` (a concurrent migration
        exported the client state first), client-side ``unknown_tenant``
        for a tenant the ROUTER still routes (the export-to-adopt window
        of an in-flight migration), and ``draining`` (planned
        decommission; the drain's own migration moves the tenant — a
        drain issued behind the router's back never migrates, so the
        grace period bounds that misuse with a structured error)."""
        wire_failures = 0
        deadline = time.monotonic() + self._reroute_grace_s
        sleep_s = 0.02
        while True:
            rec = self._routed(tenant_id)
            client = self._clients[rec.endpoint]
            try:
                return client.submit(tenant_id, *args, **kw)
            except WireError as e:
                wire_failures += 1
                if wire_failures >= 2 or not e.retryable:
                    raise
                self._host_failed(rec.endpoint, cause=e)
                if getattr(e, "batch_booked", False):
                    # delivery is the migration replay's job — but only a
                    # migration that SUCCEEDED for this tenant (it is
                    # still routed) actually replayed it; a dropped
                    # tenant's batch is gone and saying True would lie
                    with self._lock:
                        still_routed = tenant_id in self._tenants
                    if still_routed:
                        return True
                    raise ServeError(
                        "migration_failed",
                        f"tenant {tenant_id!r} could not be migrated off "
                        f"{rec.endpoint}; the in-flight batch was lost "
                        "with it.",
                    ) from e
            except ServeError as e:
                if getattr(e, "batch_booked", False):
                    # the batch sits in the replay buffer under its seq
                    # (an earlier ambiguous attempt may have been
                    # admitted): it must be delivered by a MIGRATION'S
                    # replay, never resubmitted fresh. Wait for the
                    # tenant to move off this endpoint within the grace
                    # budget; if nothing moves it, surface the error
                    # (the booking stays, a later migration still
                    # delivers exactly once).
                    old_ep = rec.endpoint
                    while time.monotonic() < deadline:
                        self._wait_not_migrating(old_ep, timeout_s=1.0)
                        with self._lock:
                            cur = self._tenants.get(tenant_id)
                        if cur is None:
                            raise ServeError(
                                "migration_failed",
                                f"tenant {tenant_id!r} was dropped while "
                                "its in-flight batch awaited migration.",
                            ) from e
                        if cur.endpoint != old_ep:
                            return True  # migrated: the replay carried it
                        time.sleep(sleep_s)
                        sleep_s = min(sleep_s * 2, 0.5)
                    raise
                if e.reason == "tenant_migrated" or (
                    e.reason == "unknown_tenant"
                    and tenant_id in self._tenants
                ):
                    pass  # re-route (possibly after the wait below)
                elif e.reason == "draining":
                    self._wait_not_migrating(rec.endpoint, timeout_s=5.0)
                else:
                    raise
                if time.monotonic() >= deadline:
                    raise ServeError(
                        "reroute_storm",
                        f"tenant {tenant_id!r}: submit could not settle "
                        f"on a host within {self._reroute_grace_s}s of "
                        "migrations/drains.",
                    ) from e
                time.sleep(sleep_s)
                sleep_s = min(sleep_s * 2, 0.5)

    def compute(self, tenant_id: str, **kw: Any) -> Any:
        rec = self._routed(tenant_id)
        if rec.replicas:
            return self._merged_compute(tenant_id, rec, **kw)
        return self._with_failover(
            tenant_id, lambda c: c.compute(tenant_id, **kw)
        )

    def sync_compute(self, tenant_id: str, **kw: Any) -> Any:
        rec = self._routed(tenant_id)
        if rec.replicas:
            raise ServeError(
                "split_tenant",
                f"tenant {tenant_id!r} is split across "
                f"{len(rec.replicas)} replicas; sync_compute cannot run a "
                "collective barrier across replica streams — use "
                "compute(), which merges replica state.",
            )
        return self._with_failover(
            tenant_id, lambda c: c.sync_compute(tenant_id, **kw)
        )

    def flush(self, tenant_id: str, **kw: Any) -> dict:
        rec = self._routed(tenant_id)
        if rec.replicas:
            return {
                rid: self._with_failover(
                    rid, lambda c, rid=rid: c.flush(rid, **kw)
                )
                for rid in list(rec.replicas)
            }
        return self._with_failover(
            tenant_id, lambda c: c.flush(tenant_id, **kw)
        )

    def detach(self, tenant_id: str, **kw: Any) -> Optional[str]:
        rec = self._routed(tenant_id)
        if rec.replicas:
            result: Optional[str] = None
            for rid in list(rec.replicas):
                try:
                    out = self._with_failover(
                        rid, lambda c, rid=rid: c.detach(rid, **kw)
                    )
                finally:
                    with self._lock:
                        self._tenants.pop(rid, None)
                    self._journal_append("remove", tenant=rid)
                if rid == tenant_id:
                    result = out
            return result
        try:
            return self._with_failover(
                tenant_id, lambda c: c.detach(tenant_id, **kw)
            )
        finally:
            with self._lock:
                self._tenants.pop(tenant_id, None)
            self._journal_append("remove", tenant=tenant_id)

    # ------------------------------------------------------ tenant splitting
    def split_tenant(self, tenant_id: str, replicas: int = 2) -> Dict[str, str]:
        """Shard a hot tenant's stream across ``replicas`` replica tenants.

        The existing stream keeps running as replica 0 under its original
        id (nothing already booked moves); replicas 1..n-1 attach as
        first-class routed tenants ``{tid}@r{k}`` with the same
        spec/knobs, preferring hosts the tenant does not already occupy.
        From the next :meth:`submit` on, batches fan out by stable hash;
        each replica owns its own seq namespace, so exactly-once (dedup,
        replay, migration) holds PER REPLICA. :meth:`compute` merges the
        replica states back into one result (``merge_collections`` for
        sliced tenants, per-member ``merge_state`` otherwise) —
        bit-identical to the single-stream fold. Atomic: a mid-split
        attach failure detaches the replicas already created and leaves
        the tenant unsplit. Returns ``{replica_id: endpoint}``."""
        if not isinstance(replicas, int) or isinstance(replicas, bool) \
                or replicas < 2:
            raise ValueError(
                f"asked for replicas={replicas!r}; a split needs an int "
                ">= 2 (1 replica is just the unsplit tenant)."
            )
        rec = self._routed(tenant_id)
        if rec.parent is not None:
            raise ServeError(
                "split_tenant",
                f"tenant {tenant_id!r} is already a replica of "
                f"{rec.parent!r}; split the parent instead.",
            )
        if rec.replicas:
            raise ServeError(
                "split_tenant",
                f"tenant {tenant_id!r} is already split into "
                f"{len(rec.replicas)} replicas.",
            )
        # replicas must start from a clean seq namespace of their own —
        # a "resume" knob would try to adopt the PARENT's checkpoint
        child_knobs = {
            k: v for k, v in rec.knobs.items() if k != "resume"
        }
        placed: Dict[str, str] = {tenant_id: rec.endpoint}
        created: List[str] = []
        try:
            for k in range(1, replicas):
                rid = _replica_id(tenant_id, k)
                with self._lock:
                    if rid in self._tenants:
                        raise ServeError(
                            "duplicate_tenant",
                            f"replica id {rid!r} is already routed.",
                        )
                try:
                    ep = self._attach_anywhere(
                        rid, rec.spec, child_knobs,
                        exclude=frozenset(placed.values()),
                    )
                except ServeError as e:
                    if e.reason != "no_hosts":
                        raise
                    # fewer hosts than replicas: spreading is best-effort,
                    # the split itself must not require fleet growth
                    ep = self._attach_anywhere(rid, rec.spec, child_knobs)
                with self._lock:
                    self._tenants[rid] = _RoutedTenant(
                        rec.spec, dict(child_knobs), ep, parent=tenant_id
                    )
                # a replica place record WITHOUT a later split record is
                # how recovery identifies (and rolls back) a torn split
                self._journal_append(
                    "place",
                    tenant=rid,
                    endpoint=ep,
                    spec=rec.spec,
                    knobs=dict(child_knobs),
                    parent=tenant_id,
                )
                placed[rid] = ep
                created.append(rid)
        except BaseException:
            for rid in created:
                try:
                    self.detach(rid)
                except (ServeError, WireError):
                    _logger.warning(
                        "router: could not roll back replica %r after a "
                        "failed split of %r", rid, tenant_id,
                    )
            raise
        with self._lock:
            rec.replicas = [
                _replica_id(tenant_id, k) for k in range(replicas)
            ]
            rec.split_next = 0
        # the split's commit record: from here recovery reconstructs the
        # fan-out set (the ordinal itself is reconciliation-derived)
        self._journal_append(
            "split", tenant=tenant_id, replicas=list(rec.replicas)
        )
        if _obs._enabled:
            _obs.counter("serve.router.splits", tenant=tenant_id)
            _trace.instant(
                "serve.router.split",
                kind="router",
                tenant=tenant_id,
                replicas=replicas,
            )
        _logger.info(
            "router: split tenant %r into %d replicas: %s",
            tenant_id, replicas, placed,
        )
        return placed

    def _merged_compute(
        self, tenant_id: str, rec: _RoutedTenant, **kw: Any
    ) -> Any:
        """Compute a split tenant: flush every replica to its durable
        checkpoint, rebuild one collection per replica from the recorded
        spec/knobs, restore, and fold replicas 1..n-1 into replica 0 —
        ``merge_collections`` re-keys cohorts by original id for sliced
        tenants; plain collections merge member-by-member. The result is
        bit-identical to computing the same batches on one stream."""
        from torcheval_tpu.metrics import SlicedMetricCollection
        from torcheval_tpu.resilience.snapshot import restore
        from torcheval_tpu.serve.daemon import EvalDaemon
        from torcheval_tpu.serve.wire import build_metrics

        paths: Dict[str, str] = {}
        for rid in list(rec.replicas):
            out = self._with_failover(
                rid, lambda c, rid=rid: c.flush(rid, **kw)
            )
            path = (out or {}).get("path")
            if not path:
                raise ServeError(
                    "no_checkpoint",
                    f"replica {rid!r} of split tenant {tenant_id!r} has "
                    "no durable checkpoint to merge (its host serves "
                    "without a checkpoint directory?).",
                )
            paths[rid] = path
        knobs = rec.knobs
        rebuilt = []
        for rid in list(rec.replicas):
            collection = EvalDaemon.build_collection(
                build_metrics(rec.spec),
                slices=knobs.get("slices"),
                approx=knobs.get("approx"),
                window_chunks=knobs.get("window_chunks"),
            )
            rebuilt.append(restore(collection, paths[rid]))
        base, others = rebuilt[0], rebuilt[1:]
        if isinstance(base, SlicedMetricCollection):
            base.merge_collections(others)
        else:
            for name, member in base.metrics.items():
                member.merge_state([o.metrics[name] for o in others])
        return base.compute()

    # --------------------------------------------------------------- health
    def health(
        self, *, migrate: bool = True, timeout_s: Any = None
    ) -> Dict[str, Any]:
        """Probe every alive host's ``daemon.health()``. A failed probe
        counts ``serve.router.probe_failures{endpoint=}`` and (with
        ``migrate=True``) marks the host dead and migrates its tenants
        right away — a monitoring loop doubles as the failure detector.
        Probes run single-attempt under ``probe_timeout_s`` (overridable
        via ``timeout_s``): one partitioned host must not blind the
        detector to the others for a whole retry ladder. Returns per-host
        health (``None`` for failed probes), the alive set, and the
        tenant placement."""
        probe_timeout = (
            timeout_s if timeout_s is not None else self._probe_timeout_s
        )
        hosts: Dict[str, Any] = {}
        for ep in self.alive:
            try:
                hosts[ep] = self._clients[ep].health(
                    timeout_s=probe_timeout, attempts=1
                )
            except (WireError, ServeError) as e:
                hosts[ep] = None
                if _obs._enabled:
                    _obs.counter(
                        "serve.router.probe_failures", endpoint=ep
                    )
                _logger.warning(
                    "router: health probe of %s failed: %s", ep, e
                )
                if migrate:
                    self._host_failed(ep, cause=e)
        return {
            "hosts": hosts,
            "alive": self.alive,
            "tenants": self.placement(),
        }

    # ------------------------------------------------------ fleet telemetry
    def subscribe_obs(
        self,
        interval_s: float = 1.0,
        *,
        stale_after_s: Optional[float] = None,
        max_events: int = 4096,
    ) -> Dict[str, str]:
        """Open one obs push stream per alive host (ISSUE 16) and fold
        what arrives into the router's fleet view.

        Each host streams O(changed) registry deltas + timeline events +
        its structured ``load_report`` on its own timer; an old host that
        rejects the op degrades to ``health()`` polling on the same
        cadence (``mode == "poll"``). ``stale_after_s`` (default three
        push intervals) is the staleness horizon :meth:`fleet_status`
        marks hosts against. Returns ``{endpoint: mode}``. Idempotent:
        re-subscribing first drops the existing streams."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        _check_timeout_s(interval_s)
        if stale_after_s is None:
            stale_after_s = 3.0 * float(interval_s)
        _check_timeout_s(stale_after_s)
        if max_events < 0:
            raise ValueError(f"max_events must be >= 0, got {max_events}.")
        self.unsubscribe_obs()
        with self._fleet_lock:
            self._obs_interval_s = float(interval_s)
            self._stale_after_s = float(stale_after_s)
            self._fleet_max_events = int(max_events)
        modes: Dict[str, str] = {}
        for ep in self.alive:
            try:
                sub = self._clients[ep].subscribe_obs(
                    interval_s,
                    on_push=lambda msg, _ep=ep: self._on_obs_push(_ep, msg),
                )
            except (WireError, ServeError) as e:
                _logger.warning(
                    "router: obs subscription to %s failed: %s", ep, e
                )
                continue
            with self._fleet_lock:
                self._obs_subs[ep] = sub
            modes[ep] = sub.mode
        return modes

    def unsubscribe_obs(self) -> None:
        """Stop every obs stream (folded fleet state is kept)."""
        with self._fleet_lock:
            subs, self._obs_subs = self._obs_subs, {}
        for sub in subs.values():
            sub.stop()

    def _on_obs_push(self, endpoint: str, msg: Dict[str, Any]) -> None:
        """Fold one pushed (or polled) obs message into the fleet view.
        Runs on the subscription's thread — only ``_fleet_lock`` here."""
        from torcheval_tpu.obs.stream import DeltaAccumulator

        with self._fleet_lock:
            rec = self._fleet.get(endpoint)
            if rec is None:
                rec = {
                    "acc": DeltaAccumulator(),
                    "events": [],
                    "events_trimmed": 0,
                    "report": None,
                    "received_at": 0.0,
                    "mode": "poll",
                    "pushes": 0,
                }
                self._fleet[endpoint] = rec
            rec["mode"] = (
                "push" if msg.get("op") == "obs_push" else "poll"
            )
            rec["received_at"] = time.monotonic()
            rec["pushes"] += 1
            if msg.get("load_report") is not None:
                rec["report"] = msg["load_report"]
            delta = msg.get("delta")
            if delta:
                rec["acc"].apply(delta)
                events = delta.get("events") or ()
                if events:
                    rec["events"].extend(events)
                    overflow = (
                        len(rec["events"]) - self._fleet_max_events
                    )
                    if overflow > 0:
                        del rec["events"][:overflow]
                        rec["events_trimmed"] += overflow
                rec["events_trimmed"] += int(
                    delta.get("events_trimmed", 0)
                )

    def fleet_status(
        self, *, stale_after_s: Optional[float] = None
    ) -> Dict[str, Any]:
        """The folded fleet view: per-host latest ``load_report``, push
        age, and a ``stale`` flag (no load report yet, or the last one is
        older than ``stale_after_s``). A killed host goes stale here
        within one horizon — BEFORE a health probe or tenant op marks it
        dead — which is the point: the stream is the early-warning
        channel, the failure detector stays authoritative for eviction.
        Pure local fold; no network, no collective rounds."""
        if stale_after_s is None:
            stale_after_s = self._stale_after_s
        if stale_after_s is None:
            stale_after_s = 3.0  # fleet view without an active stream
        now = time.monotonic()
        alive = set(self.alive)
        hosts: Dict[str, Any] = {}
        fresh_loads: List[float] = []
        with self._fleet_lock:
            endpoints = set(self._fleet) | set(self._obs_subs)
            for ep in sorted(endpoints | alive):
                rec = self._fleet.get(ep)
                sub = self._obs_subs.get(ep)
                age = (
                    now - rec["received_at"]
                    if rec is not None and rec["received_at"]
                    else None
                )
                report = rec["report"] if rec else None
                load = self._host_load(report) if report else None
                stale = age is None or age > stale_after_s
                hosts[ep] = {
                    "alive": ep in alive,
                    "mode": rec["mode"] if rec else (
                        sub.mode if sub is not None else None
                    ),
                    "subscribed": sub is not None,
                    "age_s": age,
                    "stale": stale,
                    "load_report": report,
                    "load": load,
                    "pushes": rec["pushes"] if rec else 0,
                }
                if (
                    ep in alive
                    and not stale
                    and load is not None
                    and not (report or {}).get("draining")
                ):
                    fresh_loads.append(load)
        # aggregate spare capacity across hosts with a FRESH report:
        # 1.0 = idle fleet, 0.0 = every reporting host saturated, None =
        # nobody is reporting (a policy must not scale on silence)
        headroom = (
            1.0 - sum(fresh_loads) / len(fresh_loads)
            if fresh_loads
            else None
        )
        if _obs._enabled and headroom is not None:
            _obs.gauge("serve.fleet.headroom", float(headroom))
        return {
            "schema": 1,
            "hosts": hosts,
            "alive": sorted(alive),
            "tenants": self.placement(),
            "stale_after_s": float(stale_after_s),
            "headroom": headroom,
        }

    def fleet_snapshot(self, endpoint: str) -> Dict[str, Any]:
        """The accumulated registry snapshot for one host (exact fold of
        every delta received so far, ``Registry.snapshot()`` shape)."""
        with self._fleet_lock:
            rec = self._fleet.get(endpoint)
            if rec is None:
                raise ValueError(
                    f"no obs stream state for endpoint {endpoint!r}."
                )
            return rec["acc"].snapshot()

    def fleet_chrome_trace(self, **json_kwargs: Any) -> str:
        """One Chrome/Perfetto trace for the whole fleet: every host's
        pushed timeline events merged into the router's own timeline via
        ``obs.chrome_trace(extra_events=)``, with ``pid`` = the host
        endpoint — each host renders as its own process row, tenant spans
        nested under it. Open in ``chrome://tracing`` / Perfetto."""
        from torcheval_tpu.obs import chrome_trace

        extra: List[Dict[str, Any]] = []
        with self._fleet_lock:
            for ep, rec in self._fleet.items():
                for e in rec["events"]:
                    tagged = dict(e)
                    tagged["rank"] = ep  # pid=host in the merged trace
                    extra.append(tagged)
        return chrome_trace(extra_events=extra, **json_kwargs)

    # ------------------------------------------------------------ migration
    def _wait_not_migrating(
        self, endpoint: str, *, timeout_s: float = 300.0
    ) -> None:
        """Block until no migration is in flight for ``endpoint`` (or the
        bound expires), so a caller that returns afterwards observes
        post-migration routing."""
        with self._cv:
            self._cv.wait_for(
                lambda: endpoint not in self._migrating, timeout=timeout_s
            )

    def _host_failed(self, endpoint: str, *, cause: BaseException) -> None:
        """Mark ``endpoint`` dead and migrate every tenant it held.
        Single-flight per endpoint: exactly one thread runs the
        migration; concurrent reporters of the same failure WAIT for it
        (their booked batches are delivered by the migration's replay,
        so returning before it finishes would lie to them). The network
        work runs OUTSIDE the router lock — healthy hosts keep serving
        while a dead one is migrated."""
        with self._cv:
            if endpoint in self._alive:
                self._alive.discard(endpoint)
                self._migrating.add(endpoint)
            elif endpoint in self._migrating:
                self._cv.wait_for(
                    lambda: endpoint not in self._migrating, timeout=300.0
                )
                return
            else:
                return  # already dead and fully migrated
        _logger.warning(
            "router: endpoint %s marked dead (%s); migrating its tenants.",
            endpoint,
            cause,
        )
        try:
            self._migrate_host(endpoint, reason="host_failure")
        finally:
            with self._cv:
                self._migrating.discard(endpoint)
                self._cv.notify_all()

    def drain(
        self, endpoint: str, *, timeout_s: Any = None
    ) -> Dict[str, Any]:
        """Gracefully move every tenant off ``endpoint``: the host
        checkpoints-and-evicts them all (admissions stop immediately),
        the endpoint leaves the alive set, and the tenants re-attach
        elsewhere from their fresh checkpoints. Returns
        ``{"drained": {tenant: ckpt_path}, "migrated": [tenant, ...]}``."""
        if endpoint not in self._clients:
            raise ValueError(f"unknown endpoint {endpoint!r}.")
        kw = {} if timeout_s is None else {"timeout_s": timeout_s}
        drained = self._clients[endpoint].drain(**kw)
        with self._lock:
            self._drained.add(endpoint)
        # recorded as intent: unlike a death (probes re-derive those), a
        # drain must survive recovery — the host answers probes but must
        # stay out of the alive set
        self._journal_append("host_drain", endpoint=endpoint)
        with self._cv:
            if endpoint in self._migrating:
                # a concurrent failure migration beat us to the move;
                # wait it out — the drain still checkpointed everything
                self._cv.wait_for(
                    lambda: endpoint not in self._migrating, timeout=300.0
                )
                return {"drained": drained, "migrated": []}
            self._alive.discard(endpoint)
            self._migrating.add(endpoint)
        try:
            migrated = self._migrate_host(endpoint, reason="drain")
        finally:
            with self._cv:
                self._migrating.discard(endpoint)
                self._cv.notify_all()
        return {"drained": drained, "migrated": migrated}

    def _migrate_host(self, endpoint: str, *, reason: str) -> List[str]:
        """Move every tenant routed to ``endpoint`` onto survivors.
        Caller holds the endpoint's ``_migrating`` slot (single-flight),
        NOT the router lock — the per-tenant network work must not stall
        ops against healthy hosts."""
        with self._lock:
            victims = [
                t
                for t, rec in self._tenants.items()
                if rec.endpoint == endpoint
            ]
        migrated: List[str] = []
        with _obs.span(
            "serve.router.migrate", endpoint=endpoint, reason=reason
        ):
            for tenant_id in victims:
                try:
                    self._migrate_tenant(tenant_id, endpoint, reason)
                    migrated.append(tenant_id)
                except Exception as e:  # noqa: BLE001 - containment wall
                    # a tenant that cannot migrate (no usable checkpoint —
                    # incl. a remote CheckpointError — no survivors, a
                    # checkpoint_behind refusal) is dropped from the
                    # routing table with a loud log, and the REST of the
                    # host's tenants still migrate: one tenant's bad
                    # checkpoint must never strand its neighbors on a
                    # dead endpoint. The caller's next op on the dropped
                    # tenant raises unknown_tenant, never a silent ghost.
                    _logger.error(
                        "router: tenant %r failed to migrate off %s: %s",
                        tenant_id,
                        endpoint,
                        e,
                    )
                    with self._lock:
                        self._tenants.pop(tenant_id, None)
                    self._journal_append("remove", tenant=tenant_id)
        if _obs._enabled and victims:
            _trace.instant(
                "serve.router.migrated",
                kind="serve",
                endpoint=endpoint,
                reason=reason,
                tenants=len(migrated),
            )
        return migrated

    def _migrate_tenant(
        self, tenant_id: str, from_ep: str, reason: str
    ) -> None:
        with self._lock:
            rec = self._tenants.get(tenant_id)
        if rec is None:
            return  # detached while the migration was queued
        exported = self._clients[from_ep].export_tenant(tenant_id)
        if _chaos.router_armed():
            # the drill's nastiest window: the wire state is exported,
            # the tenant is adopted nowhere — recovery must re-derive
            # everything from the journal + the hosts
            _chaos.on_router_op("migrate_exported", tenant_id)
        new_ep = self._place(tenant_id)
        client = self._clients[new_ep]
        knobs = dict(rec.knobs)
        knobs["resume"] = "auto"  # restore the shared-root checkpoint
        attach_resp = client.attach(tenant_id, rec.spec, **knobs)
        replayed = client.adopt_tenant(
            tenant_id, exported, restored_seq=int(attach_resp["last_seq"])
        )
        with self._lock:
            rec.endpoint = new_ep
            rec.placed_at = time.monotonic()  # restart the dwell clock
        self._journal_append("move", tenant=tenant_id, endpoint=new_ep)
        if _obs._enabled:
            _obs.counter("serve.router.migrations", reason=reason)
        _logger.warning(
            "router: migrated tenant %r %s -> %s (%s; checkpoint seq %d, "
            "replayed %d)",
            tenant_id,
            from_ep,
            new_ep,
            reason,
            int(attach_resp["last_seq"]),
            replayed,
        )

    # ------------------------------------------------------------ rebalance
    def rebalance(
        self,
        *,
        hot_load: float = 0.75,
        improvement: float = 0.15,
        min_dwell_s: float = 10.0,
        max_moves: int = 1,
    ) -> List[str]:
        """One load-rebalancing pass: move tenants off hot hosts onto
        the coldest eligible ones using the LIVE-host migration protocol
        (flush -> export -> drop -> re-attach -> adopt; the replay tail
        makes the move exactly-once even for batches booked mid-failure).

        Thrash-proof by construction, not by tuning: a host is hot only
        at fresh ``load >= hot_load``; a move happens only onto a target
        at least ``improvement`` colder than the source (so a move can
        never create a hotter imbalance than it cured); a tenant moves at
        most once per ``min_dwell_s`` (the dwell clock resets on every
        placement); and one pass moves at most ``max_moves`` tenants.
        Returns the moved tenant ids."""
        if max_moves < 1:
            raise ValueError(f"max_moves must be >= 1, got {max_moves}.")
        info = self._fleet_loads()
        with self._cv:
            migrating = set(self._migrating)
        loads = {
            ep: d["load"]
            for ep, d in info.items()
            if d["load"] is not None and ep not in migrating
        }
        hot = sorted(
            (
                ep
                for ep, load in loads.items()
                if load >= hot_load and not info[ep]["draining"]
            ),
            key=lambda ep: -loads[ep],
        )
        moved: List[str] = []
        if not hot:
            return moved
        now = time.monotonic()
        for src_ep in hot:
            if len(moved) >= max_moves:
                break
            targets = sorted(
                (
                    ep
                    for ep, load in loads.items()
                    if ep != src_ep
                    and not info[ep]["draining"]
                    and not info[ep]["suspect"]
                    and loads[src_ep] - load >= improvement
                ),
                key=lambda ep: loads[ep],
            )
            if not targets:
                continue
            with self._lock:
                candidates = [
                    t
                    for t, rec in self._tenants.items()
                    if rec.endpoint == src_ep
                    and now - rec.placed_at >= min_dwell_s
                ]
            for tenant_id in candidates:
                if len(moved) >= max_moves:
                    break
                if self._rebalance_move(tenant_id, src_ep, targets[0]):
                    moved.append(tenant_id)
        if moved:
            _logger.info(
                "router: rebalance moved %d tenant(s): %s", len(moved),
                moved,
            )
        return moved

    def _rebalance_move(
        self, tenant_id: str, from_ep: str, to_ep: str
    ) -> bool:
        """Move one LIVE tenant ``from_ep -> to_ep``. Unlike the failure
        path, the source is healthy: flush first (durable resume point),
        export the client wire state (racing submits start absorbing into
        the reroute grace window here), release the source slot WITHOUT a
        second checkpoint (the flush already published the resume
        source), then attach-resume + adopt on the target — the adopt
        replays only the booked-but-not-durable tail, so exactly-once
        holds across the move. If the chosen target refuses, the tenant
        falls back onto the source; a tenant that can be placed nowhere
        is dropped from the routing table with a loud log (the same
        containment wall as failure migration). Returns True if the
        tenant moved."""
        with self._lock:
            rec = self._tenants.get(tenant_id)
        if rec is None or rec.endpoint != from_ep:
            return False  # detached or moved underneath us
        src = self._clients[from_ep]
        knobs = dict(rec.knobs)
        knobs["resume"] = "auto"  # restore the shared-root checkpoint
        with _obs.span(
            "serve.router.migrate", endpoint=from_ep, reason="rebalance"
        ):
            try:
                src.flush(tenant_id)
                exported = src.export_tenant(tenant_id)
            except (ServeError, WireError) as e:
                # the source refused the hand-off: nothing moved, the
                # tenant still serves where it was — just skip this pass
                _logger.warning(
                    "router: rebalance of %r could not export from %s: "
                    "%s", tenant_id, from_ep, e,
                )
                return False
            if _chaos.router_armed():
                _chaos.on_router_op("migrate_exported", tenant_id)
            try:
                src.drop_tenant(tenant_id, checkpoint=False)
            except (ServeError, WireError) as e:
                _logger.warning(
                    "router: rebalance of %r: source %s did not release "
                    "its slot cleanly: %s", tenant_id, from_ep, e,
                )
            replayed = None
            for target in (to_ep, from_ep):
                try:
                    resp = self._clients[target].attach(
                        tenant_id, rec.spec, **knobs
                    )
                    replayed = self._clients[target].adopt_tenant(
                        tenant_id,
                        exported,
                        restored_seq=int(resp["last_seq"]),
                    )
                    new_ep = target
                    break
                except (ServeError, WireError) as e:
                    _logger.warning(
                        "router: rebalance target %s refused tenant %r: "
                        "%s", target, tenant_id, e,
                    )
            if replayed is None:
                _logger.error(
                    "router: tenant %r could not be re-placed after a "
                    "rebalance export off %s; dropping it from the "
                    "routing table.", tenant_id, from_ep,
                )
                with self._lock:
                    self._tenants.pop(tenant_id, None)
                self._journal_append("remove", tenant=tenant_id)
                return False
        with self._lock:
            rec.endpoint = new_ep
            rec.placed_at = time.monotonic()
        self._journal_append("move", tenant=tenant_id, endpoint=new_ep)
        if _obs._enabled:
            _obs.counter("serve.router.migrations", reason="rebalance")
            _obs.counter("serve.router.rebalances", endpoint=from_ep)
        if new_ep == from_ep:
            return False  # fell back home: no rebalance happened
        _logger.info(
            "router: rebalanced tenant %r %s -> %s (replayed %d)",
            tenant_id, from_ep, new_ep, replayed,
        )
        return True

    def start_rebalancer(
        self, interval_s: float = 2.0, **rebalance_kw: Any
    ) -> None:
        """Run :meth:`rebalance` on a background timer until
        :meth:`stop_rebalancer` / :meth:`close`. ``rebalance_kw`` are
        passed through to every pass (hysteresis knobs). Idempotent:
        restarting replaces the running timer."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        _check_timeout_s(interval_s)
        self.stop_rebalancer()
        stop = threading.Event()

        def _loop() -> None:
            while not stop.wait(interval_s):
                try:
                    self.rebalance(**rebalance_kw)
                except Exception:  # noqa: BLE001 - keep the timer alive
                    _logger.exception("router: rebalance pass failed")

        thread = threading.Thread(
            target=_loop,
            name="torcheval-tpu-router-rebalance",
            daemon=True,
        )
        self._rebalance_stop = stop
        self._rebalance_thread = thread
        thread.start()

    def stop_rebalancer(self) -> None:
        thread = self._rebalance_thread
        if thread is None:
            return
        self._rebalance_stop.set()
        thread.join(timeout=10.0)
        self._rebalance_thread = None

    # ------------------------------------------------------------- elasticity
    def add_host(self, endpoint: str) -> None:
        """Join one serving endpoint at runtime (scale-up). The router
        mints a client with the same factory/kwargs the constructor used,
        joins the host into the active obs stream (when one is running),
        and the very next placement can choose it — already-routed
        tenants move only via :meth:`rebalance` / failure migration, so
        joining is disruption-free. Re-adding an endpoint that died is
        allowed once its failure migration finished; re-adding a live one
        raises ``ValueError``."""
        self._wait_not_migrating(endpoint)
        client = self._client_factory(endpoint, **self._client_kwargs)
        endpoint = client.endpoint  # normalized form
        with self._cv:
            if endpoint in self._alive:
                client.close()
                raise ValueError(
                    f"endpoint {endpoint!r} is already in the fleet."
                )
            stale = self._clients.pop(endpoint, None)
            self._clients[endpoint] = client
            self._alive.add(endpoint)
            self._drained.discard(endpoint)
        self._journal_append("host_add", endpoint=endpoint)
        if stale is not None:
            stale.close()
        with self._fleet_lock:
            # a fresh process behind a recycled endpoint must not inherit
            # the dead one's folded telemetry
            self._fleet.pop(endpoint, None)
            interval_s = self._obs_interval_s
        if interval_s is not None:
            try:
                sub = client.subscribe_obs(
                    interval_s,
                    on_push=lambda msg, _ep=endpoint: self._on_obs_push(
                        _ep, msg
                    ),
                )
            except (WireError, ServeError) as e:
                _logger.warning(
                    "router: obs subscription to %s failed: %s",
                    endpoint, e,
                )
            else:
                with self._fleet_lock:
                    self._obs_subs[endpoint] = sub
        if _obs._enabled:
            _trace.instant(
                "serve.router.host_added", kind="router", endpoint=endpoint
            )
        _logger.info("router: endpoint %s joined the fleet.", endpoint)

    def remove_host(self, endpoint: str) -> Dict[str, Any]:
        """Decommission one endpoint (scale-down): stop its obs stream,
        :meth:`drain` it (checkpoint-and-evict everything, migrate the
        tenants onto survivors), then forget it entirely — unlike a
        drained host, a removed one is no longer probed or re-placeable.
        A host that is already dead is migrated-and-forgotten instead of
        drained. Returns the drain result."""
        if endpoint not in self._clients:
            raise ValueError(f"unknown endpoint {endpoint!r}.")
        with self._fleet_lock:
            sub = self._obs_subs.pop(endpoint, None)
        if sub is not None:
            sub.stop()
        try:
            out = self.drain(endpoint)
        except WireError as e:
            self._host_failed(endpoint, cause=e)
            out = {"drained": {}, "migrated": []}
        with self._cv:
            self._alive.discard(endpoint)
            self._drained.discard(endpoint)
            client = self._clients.pop(endpoint, None)
        self._journal_append("host_remove", endpoint=endpoint)
        with self._fleet_lock:
            self._fleet.pop(endpoint, None)
        if client is not None:
            client.close()
        if _obs._enabled:
            _trace.instant(
                "serve.router.host_removed",
                kind="router",
                endpoint=endpoint,
            )
        _logger.info("router: endpoint %s left the fleet.", endpoint)
        return out

    def autoscale_step(
        self,
        policy: "ScalingPolicy",
        *,
        provision: Any = None,
        decommission: Any = None,
    ) -> int:
        """Run one autoscaling decision: feed :meth:`fleet_status` to
        ``policy.decide`` and act on the signed host delta —
        ``provision()`` must return a NEW ready endpoint for each
        scale-up step (it is the deployer's hook: start the process, then
        tell the router); each scale-down step picks the coldest host,
        :meth:`remove_host`\\ s it, then hands the endpoint to
        ``decommission(endpoint)`` for teardown. A direction whose hook
        is missing is a no-op (the decision is still returned, so a
        caller can act out-of-band). Returns the policy's delta."""
        delta = int(policy.decide(self.fleet_status()))
        if delta > 0 and provision is not None:
            for _ in range(delta):
                self.add_host(provision())
        elif delta < 0 and decommission is not None:
            for _ in range(-delta):
                alive = self.alive
                if len(alive) <= 1:
                    break  # never scale to an empty fleet
                info = self._fleet_loads()
                coldest = min(
                    alive,
                    key=lambda ep: info.get(ep, {}).get("load") or 0.0,
                )
                self.remove_host(coldest)
                decommission(coldest)
        return delta


class ScalingPolicy:
    """Decide fleet resizing from one :meth:`EvalRouter.fleet_status`
    snapshot. ``decide`` returns a signed host delta: positive = add
    that many hosts, negative = drain-and-remove, 0 = hold. Policies are
    pure deciders — :meth:`EvalRouter.autoscale_step` owns the acting."""

    def decide(self, fleet_status: Dict[str, Any]) -> int:
        raise NotImplementedError


class HeadroomScalingPolicy(ScalingPolicy):
    """Scale on aggregate fleet headroom (``fleet_status()["headroom"]``,
    1.0 = idle, 0.0 = saturated): below ``scale_up_below`` asks for one
    more host, above ``scale_down_above`` releases one, inside the band
    holds. ``cooldown_s`` of mandatory quiet follows every nonzero
    decision, and ``min_hosts``/``max_hosts`` bound the fleet — with the
    dead band this makes the policy hysteretic, so load hovering at a
    threshold cannot flap the fleet. ``headroom is None`` (nobody
    reporting) always holds: a policy must not scale on silence."""

    def __init__(
        self,
        *,
        scale_up_below: float = 0.2,
        scale_down_above: float = 0.8,
        min_hosts: int = 1,
        max_hosts: Optional[int] = None,
        cooldown_s: float = 30.0,
    ) -> None:
        if not 0.0 <= scale_up_below < scale_down_above <= 1.0:
            raise ValueError(
                "need 0 <= scale_up_below < scale_down_above <= 1, got "
                f"{scale_up_below!r} / {scale_down_above!r} (the gap is "
                "the hysteresis dead band)."
            )
        if min_hosts < 1:
            raise ValueError(f"min_hosts must be >= 1, got {min_hosts}.")
        if max_hosts is not None and max_hosts < min_hosts:
            raise ValueError(
                f"max_hosts={max_hosts} is below min_hosts={min_hosts}."
            )
        if cooldown_s < 0:
            raise ValueError(
                f"cooldown_s must be >= 0, got {cooldown_s}."
            )
        self.scale_up_below = float(scale_up_below)
        self.scale_down_above = float(scale_down_above)
        self.min_hosts = int(min_hosts)
        self.max_hosts = max_hosts
        self.cooldown_s = float(cooldown_s)
        self._last_scaled_at: Optional[float] = None

    def decide(self, fleet_status: Dict[str, Any]) -> int:
        headroom = fleet_status.get("headroom")
        if headroom is None:
            return 0
        now = time.monotonic()
        if (
            self._last_scaled_at is not None
            and now - self._last_scaled_at < self.cooldown_s
        ):
            return 0
        n_hosts = len(fleet_status.get("alive") or ())
        if headroom < self.scale_up_below and (
            self.max_hosts is None or n_hosts < self.max_hosts
        ):
            self._last_scaled_at = now
            return 1
        if (
            headroom > self.scale_down_above
            and n_hosts > self.min_hosts
        ):
            self._last_scaled_at = now
            return -1
        return 0

"""Structured failure surface of the eval daemon.

Every serve-side failure is an exception with a machine-readable
``.reason`` (the :class:`~torcheval_tpu.resilience.CheckpointError`
pattern): a client can branch on the reason without parsing prose, and the
daemon's obs counters label by the same strings, so a dashboard and an
except-clause speak one vocabulary.

The hierarchy mirrors the tenant lifecycle:

* :class:`AdmissionError` — ``attach`` refused (``"capacity"``,
  ``"duplicate_tenant"``, ``"daemon_stopped"``, ``"bad_metrics"``,
  ``"no_checkpoint"``). Admission control is the front door of load
  shedding: a daemon at capacity rejects with a reason instead of growing
  an unbounded tenant table.
* :class:`BackpressureError` — a ``submit`` shed (``"queue_full"``): the
  tenant's bounded queue is full and the policy is reject-with-reason,
  never unbounded growth. Retry later, or submit with ``block=True``.
* :class:`TenantQuarantinedError` — the tenant was isolated after a fault
  its own stream caused (``"poisoned_batch"``, ``"nan_policy"``,
  ``"compute_error"``, ``"step_timeout"``); every other tenant proceeded.
  The original exception (if any) is ``__cause__``.
* :class:`TenantEvictedError` — the watchdog (or an explicit
  ``evict``/``detach(checkpoint=True)``) checkpointed the tenant's state
  and released its slot; ``.checkpoint`` is the directory to resume from
  (``attach(..., resume=...)`` restores it bit-identically).
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "ServeError",
    "AdmissionError",
    "BackpressureError",
    "TenantError",
    "TenantQuarantinedError",
    "TenantEvictedError",
]


class ServeError(RuntimeError):
    """Base class: every serve failure carries a machine-readable
    ``reason`` alongside the human message."""

    def __init__(self, reason: str, message: str) -> None:
        super().__init__(f"[{reason}] {message}")
        self.reason = reason


class AdmissionError(ServeError):
    """``attach`` refused at the front door (see module doc for reasons)."""


class BackpressureError(ServeError):
    """A ``submit`` was shed: the tenant's bounded queue is full.

    ``tenant`` names the shedding tenant. The queue bound is the
    load-shedding contract — ingestion never grows without bound, the
    producer is told *why* (``reason="queue_full"``) and can back off,
    block (``submit(..., block=True)``) or drop.
    """

    def __init__(self, reason: str, message: str, *, tenant: str) -> None:
        super().__init__(reason, message)
        self.tenant = tenant


class TenantError(ServeError):
    """Base for per-tenant terminal states; ``tenant`` names the tenant."""

    def __init__(self, reason: str, message: str, *, tenant: str) -> None:
        super().__init__(reason, message)
        self.tenant = tenant


class TenantQuarantinedError(TenantError):
    """The tenant was quarantined: a fault its own stream caused (poisoned
    batch, NaN-policy violation, a compute that raised, or a step that
    outran its deadline) isolated it with this error while every other
    tenant proceeded. Its accumulated state is considered suspect and is
    NOT checkpointed; ``detach`` the handle and re-``attach`` to start
    clean. The triggering exception, when there was one, is ``__cause__``.
    """


class TenantEvictedError(TenantError):
    """The tenant's slot was reclaimed after its state was checkpointed.

    ``checkpoint`` is the checkpoint directory
    (``<evict_dir>/<tenant_id>``); ``attach`` the same tenant id with
    identically-configured metrics and ``resume="auto"``/``"require"`` to
    restore and continue bit-identically.
    """

    def __init__(
        self,
        reason: str,
        message: str,
        *,
        tenant: str,
        checkpoint: Optional[str] = None,
    ) -> None:
        super().__init__(reason, message, tenant=tenant)
        self.checkpoint = checkpoint

"""Structured failure surface of the eval daemon.

Every serve-side failure is an exception with a machine-readable
``.reason`` (the :class:`~torcheval_tpu.resilience.CheckpointError`
pattern): a client can branch on the reason without parsing prose, and the
daemon's obs counters label by the same strings, so a dashboard and an
except-clause speak one vocabulary.

The hierarchy mirrors the tenant lifecycle:

* :class:`AdmissionError` — ``attach`` refused (``"capacity"``,
  ``"duplicate_tenant"``, ``"daemon_stopped"``, ``"bad_metrics"``,
  ``"no_checkpoint"``, ``"draining"``). Admission control is the front
  door of load shedding: a daemon at capacity rejects with a reason
  instead of growing an unbounded tenant table.
* :class:`BackpressureError` — a ``submit`` shed (``"queue_full"``): the
  tenant's bounded queue is full and the policy is reject-with-reason,
  never unbounded growth. Retry later, or submit with ``block=True``.
* :class:`TenantQuarantinedError` — the tenant was isolated after a fault
  its own stream caused (``"poisoned_batch"``, ``"nan_policy"``,
  ``"compute_error"``, ``"step_timeout"``); every other tenant proceeded.
  The original exception (if any) is ``__cause__``.
* :class:`TenantEvictedError` — the watchdog (or an explicit
  ``evict``/``detach(checkpoint=True)``) checkpointed the tenant's state
  and released its slot; ``.checkpoint`` is the directory to resume from
  (``attach(..., resume=...)`` restores it bit-identically).
* :class:`WireError` — the ISSUE 10 network layer's transport-level
  failures (``"transport"``, ``"request_timeout"``, ``"circuit_open"``,
  ``"protocol"``): the request may never have reached a daemon, so the
  *cluster* can retry it (idempotent submits make that safe), while the
  serve-side hierarchy above reports what a daemon decided.

Every error additionally carries ``retryable`` — the ONE retry
classification the wire client, the router and local callers all share:
``True`` means the same request can succeed later without operator
action (a shed under load, a daemon transiently at capacity, a network
blip), ``False`` means retrying is wrong (a quarantine, a duplicate id,
a bad metric spec) and the caller must change something first. The wire
layer marshals the flag with the error, so a remote client branches on
exactly the bit a local caller would.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "ServeError",
    "AdmissionError",
    "BackpressureError",
    "TenantError",
    "TenantQuarantinedError",
    "TenantEvictedError",
    "WireError",
]


class ServeError(RuntimeError):
    """Base class: every serve failure carries a machine-readable
    ``reason`` alongside the human message, plus ``retryable`` — whether
    the same request can succeed later without the caller changing
    anything (the shared retry-classification source of truth)."""

    # reasons (per concrete class) for which an identical retry can
    # succeed once load drains; everything else needs caller action
    _RETRYABLE_REASONS: frozenset = frozenset()

    def __init__(self, reason: str, message: str) -> None:
        super().__init__(f"[{reason}] {message}")
        self.reason = reason
        self.retryable = reason in self._RETRYABLE_REASONS


class AdmissionError(ServeError):
    """``attach`` refused at the front door (see module doc for reasons).

    Only ``"capacity"`` is retryable: the daemon is full NOW but a
    detach/eviction frees a slot. A duplicate id, a bad metric spec, a
    stopped or draining daemon, or a missing required checkpoint will
    reject an identical retry forever."""

    _RETRYABLE_REASONS = frozenset({"capacity"})


class BackpressureError(ServeError):
    """A ``submit`` was shed: the tenant's bounded queue is full.

    ``tenant`` names the shedding tenant. The queue bound is the
    load-shedding contract — ingestion never grows without bound, the
    producer is told *why* (``reason="queue_full"``) and can back off,
    block (``submit(..., block=True)``) or drop. Always retryable:
    a shed is by definition a transient load condition.
    """

    def __init__(self, reason: str, message: str, *, tenant: str) -> None:
        super().__init__(reason, message)
        self.tenant = tenant
        self.retryable = True


class TenantError(ServeError):
    """Base for per-tenant terminal states; ``tenant`` names the tenant."""

    def __init__(self, reason: str, message: str, *, tenant: str) -> None:
        super().__init__(reason, message)
        self.tenant = tenant


class TenantQuarantinedError(TenantError):
    """The tenant was quarantined: a fault its own stream caused (poisoned
    batch, NaN-policy violation, a compute that raised, or a step that
    outran its deadline) isolated it with this error while every other
    tenant proceeded. Its accumulated state is considered suspect and is
    NOT checkpointed; ``detach`` the handle and re-``attach`` to start
    clean. The triggering exception, when there was one, is ``__cause__``.
    """


class TenantEvictedError(TenantError):
    """The tenant's slot was reclaimed after its state was checkpointed.

    ``checkpoint`` is the checkpoint directory
    (``<evict_dir>/<tenant_id>``); ``attach`` the same tenant id with
    identically-configured metrics and ``resume="auto"``/``"require"`` to
    restore and continue bit-identically.
    """

    def __init__(
        self,
        reason: str,
        message: str,
        *,
        tenant: str,
        checkpoint: Optional[str] = None,
    ) -> None:
        super().__init__(reason, message, tenant=tenant)
        self.checkpoint = checkpoint


class WireError(ServeError):
    """A network-layer failure between an :class:`EvalClient` and a host.

    Reasons: ``"transport"`` (connect/send/recv failed or the connection
    died mid-request — the request may or may not have been processed;
    idempotent submits make a blind retry safe), ``"request_timeout"``
    (no response within the per-request deadline), ``"circuit_open"``
    (this host's breaker is open after consecutive failures — fail fast
    without touching the socket), ``"protocol"`` (unparseable frame: a
    version skew or a stray speaker on the port — NOT retryable, the
    peer will stay wrong). ``endpoint`` names the host. Transport-family
    failures are retryable *against the cluster*: the router responds to
    them by migrating the host's tenants, not by hammering the dead
    host.
    """

    _RETRYABLE_REASONS = frozenset(
        {"transport", "request_timeout", "circuit_open"}
    )

    def __init__(
        self, reason: str, message: str, *, endpoint: Optional[str] = None
    ) -> None:
        super().__init__(reason, message)
        self.endpoint = endpoint

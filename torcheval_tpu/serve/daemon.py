"""`EvalDaemon`: a fault-contained multi-tenant eval front end.

One long-running daemon owns the device mesh and serves many concurrent
eval streams (*tenants*), each backed by its own
:class:`~torcheval_tpu.metrics.MetricCollection`. The topology is the
decoupled many-producers / few-TPU-consumers shape of Podracer
(arXiv:2104.06272): any number of client threads enqueue host batches into
bounded per-tenant queues; ONE worker thread drains them and drives the
collections, so every device dispatch is serialized through a single
owner and a tenant can never corrupt another tenant's device work.

**Robustness is the headline property** — no tenant can take down the
daemon or another tenant:

* **Admission control** (``attach``): a daemon at ``max_tenants`` rejects
  with a structured :class:`AdmissionError` instead of growing without
  bound; duplicate ids and stopped daemons reject the same way.
* **Backpressure** (``submit``): per-tenant queues are bounded; a full
  queue sheds with :class:`BackpressureError` (reason ``"queue_full"``) —
  reject-with-reason, never unbounded growth. ``block=True`` opts into
  bounded waiting instead.
* **Fault containment**: a poisoned batch (bad shape/dtype surfacing in
  update validation, or a NaN under ``nan_policy="reject"``) or a compute
  that raises quarantines THAT tenant with a structured
  :class:`TenantQuarantinedError`; the worker moves on and every other
  tenant's results are untouched (proven bit-identical against fault-free
  oracles in ``tests/serve/``). A quarantined tenant's state is suspect
  and is never checkpointed.
* **Watchdog eviction**: a tenant idle past its ``watchdog_timeout_s`` is
  *evicted* — its state folds and checkpoints atomically via
  ``resilience.save`` into ``<evict_dir>/<tenant_id>`` and its slot frees;
  re-``attach`` with ``resume="auto"`` restores the checkpoint and the
  stream continues bit-identically. ``step_timeout_s`` additionally arms
  the PR 5 watchdog (``toolkit._sync_deadline`` + ``_run_guarded``) around
  each tenant's device step; a step that outruns it quarantines the tenant
  (the abandoned dispatch may still write its states later, so that state
  must never be checkpointed as truth — eviction is reserved for cleanly
  folded state).

**Batch coalescing.** Tenants whose batches share one ``(shape, dtype)``
signature share ONE compiled window-step program by construction: the
deferred window programs key on canonical positional member keys (ISSUE 8,
``metrics/deferred.py``), never on tenant or member names, and the
≤2-signatures-per-shape property (PR 2/6) bounds the program count per
batch shape. The scheduler serves same-signature tenants back-to-back so
the shared program stays hot, and runs control work (compute/detach)
FIRST — the per-tenant fallback lane: coalescing is opportunistic and
never delays a tenant's result to wait for a group.

Per-tenant observability: ``serve.ingest.batches{tenant=}`` /
``serve.ingest.sheds{tenant=,reason=}`` / ``serve.quarantines`` /
``serve.evictions`` counters, a ``serve.queue_depth{tenant=}`` occupancy
histogram, and a ``serve.tenant.step{tenant=}`` span per worker pass (the
rank-tagged tenant bars in the Chrome trace). ``health()`` returns a
structured daemon snapshot; ``health(sync=True)`` merges every rank's view
over ``obs.sync_snapshot()``'s one-collective exchange.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.resilience import chaos as _chaos
from torcheval_tpu.serve.errors import (
    AdmissionError,
    BackpressureError,
    ServeError,
    TenantEvictedError,
    TenantQuarantinedError,
)
from torcheval_tpu.serve.tenant import (
    TenantHandle,
    TenantStatus,
    _Promise,
    _Tenant,
)

_logger = logging.getLogger(__name__)

__all__ = ["EvalDaemon"]

_NAN_POLICIES = ("propagate", "reject")
_RESUME_POLICIES = ("auto", "never", "require")


class _NaNPolicyViolation(ValueError):
    """Internal: a float batch carried NaN under ``nan_policy="reject"``."""


def _ingest_anchor():
    """Newest in-flight execution anchor (the PR 6 donated-hold registry,
    falling back to the last window-step output) — the guard a pooled
    staging buffer's release rides so its slot is not recycled while a
    program that read it may still be running."""
    from torcheval_tpu.metrics import deferred as _deferred

    return _deferred.inflight_anchor()


def _batch_signature(args) -> tuple:
    """Host-side batch signature for coalesced scheduling: shapes + dtypes
    of the queued (host) arrays. Cheap — attribute reads only."""
    return tuple(
        (
            tuple(getattr(a, "shape", ()) or ()),
            str(getattr(a, "dtype", type(a).__name__)),
        )
        for a in args
    )


class EvalDaemon:
    """The persistent multi-tenant eval service (see module doc).

    Example::

        from torcheval_tpu.serve import EvalDaemon
        from torcheval_tpu.metrics import MulticlassAccuracy

        with EvalDaemon(max_tenants=128) as daemon:
            h = daemon.attach("user-42", {"acc": MulticlassAccuracy(num_classes=10)})
            for scores, labels in stream:
                h.submit(scores, labels)       # async, bounded, shed-with-reason
            results = h.compute()              # {"acc": ...}
            h.detach()

    ``start()``/``stop()`` (or the context manager) bound the worker
    thread's lifetime. All client methods are thread-safe.
    """

    def __init__(
        self,
        *,
        max_tenants: int = 64,
        queue_capacity: int = 32,
        evict_dir: Optional[str] = None,
        evict_keep_last: int = 2,
        watchdog_interval_s: float = 0.25,
        metrics_port: Optional[int] = None,
    ) -> None:
        if max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, got {max_tenants}.")
        if queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1, got {queue_capacity}."
            )
        self._max_tenants = max_tenants
        self._queue_capacity = queue_capacity
        self._evict_dir_arg = evict_dir
        self._evict_dir: Optional[str] = evict_dir
        self._evict_keep_last = evict_keep_last
        self._watchdog_interval_s = watchdog_interval_s
        # metrics_port: bind the stdlib Prometheus/health scrape endpoint
        # (obs/httpd.py) on start(); 0 = ephemeral port, None = no endpoint
        self._metrics_port = metrics_port
        self._metrics_server = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants: Dict[str, _Tenant] = {}
        self._attaching: set = set()  # reserved ids mid-admission
        self._running = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._started_at: Optional[float] = None
        self._totals = {"attached": 0, "quarantined": 0, "evicted": 0}
        # aggregate submit/step latency EWMAs (alpha below) feeding
        # load_report(); plain floats, no registry round trip
        self._lat_ewma: Dict[str, float] = {}
        # callbacks the wire layer registers to get a final obs push out
        # before telemetry consumers would otherwise see a silent stop
        self._flush_hooks: list = []

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "EvalDaemon":
        with self._cond:
            if self._running:
                return self
            self._running = True
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._worker_loop,
                name="torcheval-tpu-serve-worker",
                daemon=True,
            )
            self._thread.start()
        if self._metrics_port is not None and self._metrics_server is None:
            from torcheval_tpu.obs.httpd import MetricsServer

            self._metrics_server = MetricsServer(
                port=self._metrics_port,
                health_provider=self.load_report,
            ).start()
        return self

    @property
    def metrics_address(self) -> Optional[tuple]:
        """``(host, port)`` of the scrape endpoint, or ``None`` when the
        daemon was built without ``metrics_port``."""
        if self._metrics_server is None:
            return None
        return self._metrics_server.address

    def stop(self, *, timeout: Optional[float] = 10.0) -> None:
        """Stop the worker. Outstanding compute/detach promises are failed
        with a structured ``daemon_stopped`` error; tenant tables stay
        readable (``health()``) but every handle op raises afterwards.
        ``timeout`` bounds the worker join (``None`` = wait forever) and
        is validated at this boundary like every other deadline knob — a
        NaN/inf/non-positive join budget must raise here, not silently
        turn the join into a no-op or a hang."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        _check_timeout_s(timeout)
        with self._cond:
            if not self._running:
                return
            self._running = False
            self._cond.notify_all()
        # final obs flush BEFORE the worker join: subscribers get the last
        # delta (including this stop's own instruments) while the wire
        # publishers are still alive
        self._notify_flush_hooks()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    # ---------------------------------------------------------- flush hooks
    def _add_flush_hook(self, cb) -> None:
        """Register ``cb()`` to run on ``drain()`` and ``stop()`` — the
        obs push channel's final-flush seam (``wire.EvalServer`` wires its
        publishers here so a subscriber's last delta is never lost to a
        graceful shutdown)."""
        with self._lock:
            if cb not in self._flush_hooks:
                self._flush_hooks.append(cb)

    def _remove_flush_hook(self, cb) -> None:
        with self._lock:
            try:
                self._flush_hooks.remove(cb)
            except ValueError:
                pass

    def _notify_flush_hooks(self) -> None:
        with self._lock:
            hooks = list(self._flush_hooks)
        for cb in hooks:
            try:
                cb()
            except Exception:  # noqa: BLE001 - shutdown must proceed
                _logger.exception("serve: obs flush hook raised; continuing")

    def __enter__(self) -> "EvalDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ admission
    def attach(
        self,
        tenant_id: str,
        metrics: Any,
        *,
        nan_policy: str = "propagate",
        watchdog_timeout_s: Optional[float] = None,
        step_timeout_s: Optional[float] = None,
        queue_capacity: Optional[int] = None,
        resume: str = "auto",
        window_chunks: Optional[int] = None,
        approx=None,
        slices=None,
    ) -> TenantHandle:
        """Admit one tenant and return its handle.

        ``metrics`` is a ``Metric``, a ``{name: Metric}`` dict, or a
        prebuilt ``MetricCollection`` — the tenant's whole eval stream
        folds through it. ``nan_policy="reject"`` quarantines the tenant
        on the first float batch carrying NaN (an O(batch) host scan per
        submit-side batch, priced in docs). ``watchdog_timeout_s`` arms
        idle eviction; ``step_timeout_s`` arms the per-step PR 5 watchdog.
        ``resume`` controls eviction-checkpoint restore for this tenant id:
        ``"auto"`` restores iff a checkpoint exists, ``"require"`` raises
        ``AdmissionError(reason="no_checkpoint")`` without one, ``"never"``
        starts clean. ``window_chunks`` caps this tenant's eval-window
        occupancy (the deferred chunk-count valve): a lower cap closes
        windows more often, which bounds per-tenant pending HBM and sets
        the double-buffering cadence — window N+1 fills and transfers
        while window N's step executes (ISSUE 11). ``approx`` (ROADMAP
        4(c)) opts this tenant's curve/cache metrics into bounded-memory
        sketch state (``True`` = family-default bucket count, an int = the
        bucket count — the metric constructors' ``approx=`` contract,
        applied at admission): every member with an approx mode switches;
        members whose state is already bounded (counters, regressions,
        ``Quantile``) pass through, and a spec where NO member has an
        approx mode — or where a member supports it but cannot switch
        (already-streamed state, a multiclass curve without
        ``num_classes``) — rejects as ``bad_metrics``. A tenant re-attached
        with a different ``approx`` than its eviction checkpoint cannot
        restore into the changed state schema — use ``resume="never"`` to
        start it clean. ``slices`` (ISSUE 15) opts this tenant into
        per-cohort eval: ``True`` (defaults), an int (initial dense
        capacity), or ``{"capacity": int, "curve_bucket_bits": int,
        "mesh_axis": str}`` — the tenant's metrics become a
        :class:`~torcheval_tpu.metrics.SlicedMetricCollection`, every
        ``submit`` must carry the ``slice_ids`` integer column FIRST, and
        ``compute`` returns per-slice results keyed by original ids.
        ``slices={"mesh_axis": ...}`` (ISSUE 17) additionally shards the
        slice axis of every member state across that named axis of a flat
        all-local-devices mesh — per-device slice state and the sketch's
        int32 extent bound both shrink by the device count (the axis name
        is a plain wire string; device handles never cross the wire). The
        sliceability of every member is validated BEFORE the ``approx``
        knob commits (validate-then-commit covers slice expansion too): a
        spec with an unsliceable member rejects as ``bad_metrics`` without
        half-switching anything. Raises :class:`AdmissionError`
        (``"capacity"`` / ``"duplicate_tenant"`` / ``"daemon_stopped"`` /
        ``"bad_metrics"``) instead of ever over-admitting.
        """
        if nan_policy not in _NAN_POLICIES:
            raise ValueError(
                f"nan_policy must be one of {_NAN_POLICIES}, got {nan_policy!r}."
            )
        if resume not in _RESUME_POLICIES:
            raise ValueError(
                f"resume must be one of {_RESUME_POLICIES}, got {resume!r}."
            )
        # the same boundary validation the sync APIs perform: a degenerate
        # deadline must reject ADMISSION, not fire later inside the worker
        # (where a ValueError from the deadline machinery would be
        # misclassified as tenant poison) or silently disarm the watchdog
        # (nan never compares >= the idle age)
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        for knob, value in (
            ("watchdog_timeout_s", watchdog_timeout_s),
            ("step_timeout_s", step_timeout_s),
        ):
            try:
                _check_timeout_s(value)
            except ValueError as e:
                raise ValueError(f"{knob}: {e}") from None
        if queue_capacity is not None and queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1, got {queue_capacity}."
            )
        if window_chunks is not None and (
            not isinstance(window_chunks, int) or window_chunks < 1
        ):
            raise ValueError(
                f"window_chunks must be an int >= 1, got {window_chunks!r}."
            )
        with self._cond:
            if not self._running:
                self._count_admission("rejected", "daemon_stopped")
                raise AdmissionError(
                    "daemon_stopped",
                    f"cannot attach {tenant_id!r}: the daemon is not running.",
                )
            if self._draining:
                self._count_admission("rejected", "draining")
                raise AdmissionError(
                    "draining",
                    f"cannot attach {tenant_id!r}: this daemon is draining "
                    "(its tenants are being migrated off-host).",
                )
            if tenant_id in self._tenants or tenant_id in self._attaching:
                self._count_admission("rejected", "duplicate_tenant")
                raise AdmissionError(
                    "duplicate_tenant",
                    f"tenant {tenant_id!r} is already attached; detach it "
                    "first.",
                )
            if (
                len(self._tenants) + len(self._attaching)
                >= self._max_tenants
            ):
                self._count_admission("rejected", "capacity")
                raise AdmissionError(
                    "capacity",
                    f"daemon is at max_tenants={self._max_tenants}; "
                    f"rejecting {tenant_id!r} (load shedding at the front "
                    "door — retry after a detach/eviction).",
                )
            # a malformed slices config raises raw ValueError (knob
            # validation, not spec rejection) exactly as before the
            # builder extraction; build_collection re-normalizes inside
            self._normalize_slices(slices)
            try:
                collection = self.build_collection(
                    metrics,
                    slices=slices,
                    approx=approx,
                    window_chunks=window_chunks,
                )
            except ValueError as e:
                self._count_admission("rejected", "bad_metrics")
                raise AdmissionError(
                    "bad_metrics", f"tenant {tenant_id!r} {e}"
                ) from e
            ckpt_dir = self._tenant_ckpt_dir(tenant_id, create=False)
            # reserve the id + a capacity slot, then RELEASE the lock for
            # the checkpoint I/O below: a migration restore can take long
            # enough that holding the daemon-wide lock across it would
            # stall every live tenant's submit on this host
            self._attaching.add(tenant_id)
        do_resume = False
        resumed_seq = 0
        try:
            if resume != "never":
                from torcheval_tpu.resilience.snapshot import latest_checkpoint

                has_ckpt = (
                    ckpt_dir is not None
                    and latest_checkpoint(ckpt_dir) is not None
                )
                if resume == "require" and not has_ckpt:
                    self._count_admission("rejected", "no_checkpoint")
                    raise AdmissionError(
                        "no_checkpoint",
                        f"resume='require' but no eviction checkpoint exists "
                        f"for tenant {tenant_id!r} under {ckpt_dir!r}.",
                    )
                do_resume = has_ckpt
            if do_resume:
                # restore BEFORE the tenant is visible: a failed restore
                # (schema drift) must reject admission, not quarantine a
                # half-born tenant. Corrupt BYTES are different (ISSUE
                # 20): a bit-flipped generation is quarantined and the
                # walk falls back to the previous durable one — the
                # tenant degrades to an older watermark and the client
                # replay buffer heals the gap, instead of the whole
                # attach rejecting over storage rot.
                from torcheval_tpu.resilience.snapshot import (
                    _CORRUPT_REASONS,
                    CheckpointError,
                    _resolve_ckpt,
                    quarantine_checkpoint,
                    read_extra,
                    restore,
                )

                fell_back = 0
                while True:
                    # resolve the checkpoint ONCE per attempt and use the
                    # same directory for both the state and the watermark
                    # — resolving twice would let a concurrent publish
                    # (e.g. a partitioned old host still flushing into
                    # the shared root) slip a newer manifest between the
                    # two reads, arming the dedup window ahead of the
                    # restored state and silently dropping replayed
                    # batches. For seq-tracked tenants prefer the HIGHEST
                    # acked watermark over the newest step: a
                    # partitioned-but-alive old host can publish a stale
                    # checkpoint into the shared root AFTER the tenant
                    # migrated, and "newest step" would resurrect it.
                    try:
                        ckpt = self._best_serve_ckpt(
                            ckpt_dir
                        ) or _resolve_ckpt(ckpt_dir)
                    except CheckpointError:
                        ckpt = None
                    if ckpt is None:
                        # the lineage ran dry: every generation was
                        # corrupt and is now quarantined. "require"
                        # promised a restorable checkpoint — reject;
                        # "auto" degrades to a clean start (the replay
                        # buffer is the only healer left).
                        if resume == "require":
                            self._count_admission(
                                "rejected", "no_checkpoint"
                            )
                            raise AdmissionError(
                                "no_checkpoint",
                                f"resume='require' but every checkpoint "
                                f"generation for tenant {tenant_id!r} "
                                f"under {ckpt_dir!r} was corrupt "
                                f"({fell_back} quarantined).",
                            )
                        do_resume = False
                        break
                    try:
                        restore(collection, ckpt)
                    except CheckpointError as e:
                        if e.reason not in _CORRUPT_REASONS:
                            raise
                        quarantine_checkpoint(ckpt)
                        fell_back += 1
                        continue
                    # the wire-sequence watermark rides the manifest
                    # (written atomically with the state it describes):
                    # every batch with seq <= resumed_seq is IN the
                    # restored state, so the dedup window re-arms exactly
                    # where the checkpoint left it and a client replaying
                    # its un-acked window after a migration can never
                    # double-apply a checkpointed batch
                    resumed_seq = int(
                        read_extra(ckpt).get("serve", {}).get("acked_seq", 0)
                    )
                    if fell_back and _obs._enabled:
                        _obs.counter(
                            "resilience.checkpoint.fallback_restores"
                        )
                    break
        except BaseException:
            with self._cond:
                self._attaching.discard(tenant_id)
            raise
        with self._cond:
            self._attaching.discard(tenant_id)
            if not self._running or self._draining:
                # the daemon stopped/drained while we restored: reject —
                # committing now would strand a tenant the drain's
                # eviction sweep already missed
                reason = "daemon_stopped" if not self._running else "draining"
                self._count_admission("rejected", reason)
                raise AdmissionError(
                    reason,
                    f"cannot attach {tenant_id!r}: the daemon began "
                    f"{reason.replace('_', ' ')} during admission.",
                )
            self._seq += 1
            tenant = _Tenant(
                tenant_id,
                collection,
                capacity=(
                    queue_capacity
                    if queue_capacity is not None
                    else self._queue_capacity
                ),
                nan_policy=nan_policy,
                watchdog_timeout_s=watchdog_timeout_s,
                step_timeout_s=step_timeout_s,
                seq=self._seq,
            )
            tenant.last_seq = tenant.applied_seq = tenant.durable_seq = (
                resumed_seq
            )
            self._tenants[tenant_id] = tenant
            self._totals["attached"] += 1
            self._count_admission("accepted", "resumed" if do_resume else "new")
            if _obs._enabled:
                _obs.gauge("serve.tenants.active", float(len(self._tenants)))
        return TenantHandle(self, tenant)

    @staticmethod
    def build_collection(
        metrics,
        *,
        slices=None,
        approx=None,
        window_chunks=None,
    ):
        """Construct the servable collection EXACTLY as attach admission
        does — the ONE constructor shared by daemon admission and the
        router's split-tenant merged compute (ISSUE 19: a replica's
        flush checkpoint restores only into an identically-built
        collection, so the merge path must never re-implement this).
        Order matters and is the admission contract: sliceability dry
        pass BEFORE the ``approx`` knob commits (validate-then-commit
        covers slice-expanded members), then the sketch switch, then the
        slice expansion, then the per-instance window valve. Raises
        ``ValueError`` carrying the admission message tail; ``attach``
        prefixes the tenant id and wraps it as
        ``AdmissionError("bad_metrics")``."""
        from torcheval_tpu.metrics.collection import MetricCollection

        try:
            collection = (
                metrics
                if isinstance(metrics, MetricCollection)
                else MetricCollection(metrics)
            )
        except (TypeError, ValueError) as e:
            raise ValueError(f"metrics are not servable: {e}") from e
        slice_cfg = EvalDaemon._normalize_slices(slices)
        from torcheval_tpu.metrics.sliced import (
            SlicedMetricCollection,
            check_sliceable,
        )

        if slice_cfg is not None and not isinstance(
            collection, SlicedMetricCollection
        ):
            # sliceability dry pass BEFORE the approx knob commits:
            # validate-then-commit must cover slice-expanded members
            # too — a spec with one unsliceable member rejects here
            # without any member having been switched to sketch state
            try:
                for m in collection.metrics.values():
                    check_sliceable(m, approx=approx)
            except ValueError as e:
                raise ValueError(
                    f"cannot run slices={slices!r}: {e}"
                ) from e
        if approx is not None and approx is not False:
            # per-tenant sketch opt-in (ROADMAP 4(c)): switch every
            # approx-capable member at admission; reject when the spec
            # has no capable member or a member cannot switch.
            # Validate-then-commit: the dry pass runs EVERY member's
            # checks before anything mutates, so a rejection never
            # leaves a caller-held instance half-switched into a
            # changed state schema.
            from torcheval_tpu.sketch.cache import enable_metric_approx

            try:
                capable = [
                    enable_metric_approx(m, approx, dry_run=True)
                    for m in collection.metrics.values()
                ]
            except ValueError as e:
                raise ValueError(
                    f"cannot run approx={approx!r}: {e}"
                ) from e
            if not any(capable):
                raise ValueError(
                    f"asked for approx={approx!r} but no metric in its "
                    "spec has an approx mode."
                )
            for m in collection.metrics.values():
                enable_metric_approx(m, approx)
        if slice_cfg is not None and not isinstance(
            collection, SlicedMetricCollection
        ):
            try:
                collection = SlicedMetricCollection(
                    collection.metrics, **slice_cfg
                )
            except ValueError as e:
                raise ValueError(
                    f"cannot run slices={slices!r}: {e}"
                ) from e
        if window_chunks is not None:
            # per-instance valve override (the collection's budget
            # check reads the probe member; each member's own 2x
            # self-valve scales off the same attribute)
            for m in getattr(collection, "_deferred", {}).values():
                m._DEFER_MAX_CHUNKS = window_chunks
        return collection

    @staticmethod
    def _normalize_slices(slices) -> Optional[dict]:
        """``slices`` knob → SlicedMetricCollection kwargs (or ``None`` =
        unsliced). ``True`` = defaults, an int = initial dense capacity, a
        dict allows ``capacity`` / ``curve_bucket_bits`` / ``mesh_axis``
        (a string axis NAME — it travels the wire as plain JSON and the
        daemon's collection builds the flat all-local-devices mesh, so a
        client never ships device handles). Validated at the admission
        boundary so a typo'd config rejects the attach instead of
        surfacing later as tenant poison."""
        if slices is None or slices is False:
            return None
        if slices is True:
            return {}
        if isinstance(slices, int):
            return {"capacity": slices}
        if isinstance(slices, dict):
            allowed = {"capacity", "curve_bucket_bits", "mesh_axis"}
            unknown = set(slices) - allowed
            if unknown:
                raise ValueError(
                    f"unknown slices config keys {sorted(unknown)}; "
                    f"allowed: {sorted(allowed)}."
                )
            out = {}
            for k, v in slices.items():
                if k == "mesh_axis":
                    if not isinstance(v, str) or not v:
                        raise ValueError(
                            "slices['mesh_axis'] must be a non-empty "
                            f"axis-name string, got {v!r}."
                        )
                    out[k] = v
                else:
                    out[k] = int(v)
            return out
        raise ValueError(
            "slices must be True, an int capacity, or a config dict, "
            f"got {slices!r}."
        )

    @staticmethod
    def _best_serve_ckpt(ckpt_dir: Optional[str]) -> Optional[str]:
        """The published checkpoint with the highest serve acked-seq
        watermark (ties -> newest step; zero-padded names sort by step).
        For tenants never driven over the wire every watermark is 0 and
        this degenerates to newest-step, exactly the old behavior."""
        from torcheval_tpu.resilience.snapshot import (
            CheckpointError,
            list_checkpoints,
            read_extra,
        )

        if ckpt_dir is None:
            return None
        best, best_key = None, None
        for ckpt in list_checkpoints(ckpt_dir):
            try:
                acked = int(
                    read_extra(ckpt).get("serve", {}).get("acked_seq", 0)
                )
            except (CheckpointError, TypeError, ValueError):
                continue  # unreadable manifest: restore would reject it
            key = (acked, ckpt)
            if best_key is None or key > best_key:
                best, best_key = ckpt, key
        return best

    def _count_admission(self, result: str, reason: str) -> None:
        if _obs._enabled:
            _obs.counter("serve.admissions", result=result, reason=reason)

    def _tenant_ckpt_dir(
        self, tenant_id: str, *, create: bool
    ) -> Optional[str]:
        if self._evict_dir is None:
            if not create and self._evict_dir_arg is None:
                # no directory configured and none materialized yet: there
                # can be no checkpoint to resume from
                return None
            self._evict_dir = self._evict_dir_arg or tempfile.mkdtemp(
                prefix="torcheval_tpu_serve_evict_"
            )
        # tenant ids become directory names; keep them filesystem-safe
        safe = "".join(
            c if (c.isalnum() or c in "-_.") else "_" for c in tenant_id
        )
        return os.path.join(self._evict_dir, safe)

    # ------------------------------------------------------------ ingestion
    def _submit(
        self,
        tenant: _Tenant,
        args: tuple,
        *,
        block: bool,
        timeout: Optional[float],
        seq: Optional[int] = None,
        stage: Any = None,
        gapless: bool = False,
    ) -> bool:
        """Admit one batch. ``seq`` is the wire client's per-tenant
        monotonic sequence number: a submit at or below the tenant's
        admitted watermark is a replay of a batch this daemon already
        holds (an ambiguous-failure retry — at-least-once on the wire)
        and is acknowledged WITHOUT re-applying (exactly-once into the
        metric state). Returns ``True`` when the batch was admitted,
        ``False`` when it was deduplicated. The dedup check re-runs
        after every capacity wait: two retries of one seq can block in
        the wait side by side, and only the first may append.

        ``stage`` (the pooled staging buffer backing ``args``, ISSUE 11)
        is owned by this call from here on: it rides the queue entry and
        is released after the worker's device placement, or released
        RIGHT HERE on every path that does not enqueue (dedup, shed,
        drain reject, dead tenant) — a shed batch must never leak its
        staging slot.

        ``gapless`` (ISSUE 18, set by the pipelined wire path) enforces
        contiguous per-tenant admission: a ``seq`` ABOVE ``last admitted
        + 1`` is refused with a retryable ``seq_gap`` reject instead of
        admitted. With several frames of one tenant in flight at once,
        admitting past a hole (an earlier seq that shed) would ratchet
        the dedup watermark over it — the eventual replay of the missing
        seq would then read as a duplicate and be silently swallowed.
        The refusal makes every out-of-order interleaving self-healing:
        nothing lands past the hole, the client's resend redelivers the
        tail in order. Lock-step submits never set it (they are
        contiguous by construction, and migration tests drive fresh
        daemons at restored watermarks the daemon never saw)."""
        t0 = time.perf_counter()
        deadline = (
            time.monotonic() + timeout
            if (block and timeout is not None)
            else None
        )
        try:
            with self._cond:
                while True:
                    self._check_live(tenant)
                    if seq is not None and seq <= tenant.last_seq:
                        # dedup BEFORE the draining check: a replay of an
                        # already-admitted seq must get its duplicate ack
                        # even mid-drain — a "draining" reject here would
                        # make the client think the batch was never admitted
                        # and resubmit it under a fresh seq elsewhere while
                        # the drain checkpoint also carries it (double-apply)
                        tenant.dupes += 1
                        if _obs._enabled:
                            _obs.counter(
                                "serve.ingest.dupes", tenant=tenant.id
                            )
                        return False
                    if (
                        gapless
                        and seq is not None
                        and seq > tenant.last_seq + 1
                    ):
                        # pipelined out-of-order arrival (docstring):
                        # refuse rather than ratchet the watermark over
                        # the hole; no capacity consumed, no shed counted
                        # against the tenant — the earlier seq's failure
                        # already was
                        raise BackpressureError(
                            "seq_gap",
                            f"tenant {tenant.id!r}: seq {seq} arrived with "
                            f"seq {tenant.last_seq + 1} still unadmitted; "
                            "redeliver in order (an earlier pipelined "
                            "frame shed or failed).",
                            tenant=tenant.id,
                        )
                    if self._draining:
                        raise ServeError(
                            "draining",
                            f"tenant {tenant.id!r}: this daemon is draining; "
                            "resubmit after the router migrates the tenant.",
                        )
                    if len(tenant.queue) < tenant.capacity:
                        break
                    if not block:
                        self._shed(tenant, "queue_full")
                    remaining = (
                        None
                        if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        self._shed(tenant, "queue_full")
                    if not self._cond.wait(timeout=remaining):
                        self._shed(tenant, "queue_full")
                tenant.ingested += 1
                step = tenant.ingested
                if seq is not None:
                    tenant.last_seq = seq
                if not _chaos.ingest_armed():
                    tenant.queue.append(
                        ("batch", (seq, args, stage, None), None)
                    )
                    stage = None  # the queue entry owns it now
                    tenant.last_activity = time.monotonic()
                    depth = len(tenant.queue)
                    self._cond.notify_all()
                    args = None
            if args is not None:
                # chaos slow path (test-only): the fault fires at the queue
                # boundary for a batch that PASSED admission — only admitted
                # batches advance ``step``, so a shed can never consume the
                # one-shot fault — and OUTSIDE the lock, so an ingestion
                # delay stalls only this producer. The re-acquire below may
                # transiently exceed the queue bound by the number of
                # concurrent producers mid-hook; chaos is disarmed in
                # production, where the bound is exact.
                args = _chaos.on_ingest(tenant.id, step, args)
                with self._cond:
                    self._check_live(tenant)
                    tenant.queue.append(
                        ("batch", (seq, args, stage, None), None)
                    )
                    stage = None
                    tenant.last_activity = time.monotonic()
                    depth = len(tenant.queue)
                    self._cond.notify_all()
        finally:
            if stage is not None:
                stage.release()
        elapsed = time.perf_counter() - t0
        self._ewma("submit", elapsed)
        if _obs._enabled:
            _obs.counter("serve.ingest.batches", tenant=tenant.id)
            _obs.histo("serve.queue_depth", float(depth), tenant=tenant.id)
            # admission-to-enqueue latency: the SLO drill's instrument (a
            # chaos ingest_delay stalls exactly this path) and the
            # load_report's submit_p99_s source
            _obs.histo("serve.submit.latency", elapsed, tenant=tenant.id)
        return True

    _EWMA_ALPHA = 0.2

    def _ewma(self, key: str, seconds: float) -> None:
        prev = self._lat_ewma.get(key)
        self._lat_ewma[key] = (
            seconds
            if prev is None
            else prev + self._EWMA_ALPHA * (seconds - prev)
        )

    def _shed(self, tenant: _Tenant, reason: str) -> None:
        tenant.sheds += 1
        if _obs._enabled:
            _obs.counter("serve.ingest.sheds", tenant=tenant.id, reason=reason)
        raise BackpressureError(
            reason,
            f"tenant {tenant.id!r} queue is full "
            f"({tenant.capacity} batches pending); batch shed — back off, "
            "block=True, or raise queue_capacity.",
            tenant=tenant.id,
        )

    def _check_live(self, tenant: _Tenant) -> None:
        """Raise the tenant's terminal error (or a daemon error) if this
        tenant can no longer accept work. Caller holds the lock."""
        if not self._running:
            raise ServeError(
                "daemon_stopped", "the daemon has been stopped."
            )
        if tenant.status is not TenantStatus.ACTIVE:
            if tenant.error is not None:
                raise tenant.error
            raise ServeError(
                "tenant_detached",
                f"tenant {tenant.id!r} is {tenant.status.value}.",
            )

    def _request(
        self,
        tenant: _Tenant,
        kind: str,
        *,
        timeout: Optional[float],
        payload: Any = None,
    ) -> Any:
        promise = _Promise()
        with self._cond:
            self._check_live(tenant)
            tenant.queue.append((kind, payload, promise))
            tenant.last_activity = time.monotonic()
            self._cond.notify_all()
        return promise.result(timeout)

    def _detach(
        self,
        tenant: _Tenant,
        *,
        checkpoint: bool,
        timeout: Optional[float],
    ) -> Optional[str]:
        with self._cond:
            if tenant.status is not TenantStatus.ACTIVE or not self._running:
                # terminal tenants (and stopped daemons) detach directly:
                # there is no worker round trip to make, only a slot to
                # clear — the checkpoint, if the tenant was evicted, already
                # exists and its path is on the error
                self._tenants.pop(tenant.id, None)
                prev = tenant.status
                if tenant.status is TenantStatus.ACTIVE:
                    tenant.status = TenantStatus.DETACHED
                if _obs._enabled:
                    _obs.gauge(
                        "serve.tenants.active", float(len(self._tenants))
                    )
                return (
                    tenant.error.checkpoint
                    if (
                        prev is TenantStatus.EVICTED
                        and isinstance(tenant.error, TenantEvictedError)
                    )
                    else None
                )
        return self._request(
            tenant,
            "detach",
            timeout=timeout,
            payload={"checkpoint": checkpoint, "evict": False},
        )

    def evict(
        self, tenant_id: str, *, timeout: Optional[float] = None
    ) -> str:
        """Explicitly evict an active tenant: drain its queue, fold and
        checkpoint its state, free its slot. Returns the checkpoint path;
        the handle's next op raises :class:`TenantEvictedError` carrying
        the same path. (The watchdog calls the same machinery for tenants
        idle past ``watchdog_timeout_s``.)"""
        with self._cond:
            tenant = self._tenants.get(tenant_id)
            if tenant is None or tenant.status is not TenantStatus.ACTIVE:
                raise ServeError(
                    "unknown_tenant",
                    f"no active tenant {tenant_id!r} to evict.",
                )
        return self._request(
            tenant,
            "detach",
            timeout=timeout,
            payload={"checkpoint": True, "evict": True},
        )

    def drain(
        self, *, timeout: Optional[float] = None
    ) -> Dict[str, Optional[str]]:
        """Gracefully hand every tenant off this host (ISSUE 10): stop
        admitting work (new ``attach``/``submit`` reject with a structured
        ``"draining"`` reason), then evict each ACTIVE tenant — drain its
        queue, fold + checkpoint atomically, free the slot — and return
        ``{tenant_id: checkpoint_path}``. A cluster router calls this
        before taking a host down, then re-attaches the tenants elsewhere
        from the returned checkpoints; quarantined tenants have no
        trustworthy state to hand off and are omitted. The daemon stays
        up (``health()`` keeps answering) so the router can verify the
        drain; ``stop()`` it afterwards. ``timeout`` bounds each tenant's
        eviction round trip."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        _check_timeout_s(timeout)
        with self._cond:
            if not self._running:
                raise ServeError(
                    "daemon_stopped", "cannot drain a stopped daemon."
                )
            self._draining = True
            victims = [
                t.id
                for t in self._tenants.values()
                if t.status is TenantStatus.ACTIVE
            ]
        out: Dict[str, Optional[str]] = {}
        for tid in victims:
            try:
                out[tid] = self.evict(tid, timeout=timeout)
            except ServeError:
                # quarantined mid-drain, or detached by a racing client:
                # either way there is no state to hand off
                continue
        if _obs._enabled:
            _obs.counter("serve.drains")
            _trace.instant(
                "serve.drained", kind="serve", tenants=len(out)
            )
        # subscribers see the drain's own counters/trace in a final push
        # rather than learning about it from a dead socket
        self._notify_flush_hooks()
        return out

    # ---------------------------------------------------------- worker side
    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    self._fail_pending_locked()
                    return
                if not self._has_work_locked():
                    self._cond.wait(timeout=self._watchdog_interval_s)
                if not self._running:
                    self._fail_pending_locked()
                    return
                plans = self._plan_pass_locked()
            self._stage_pass(plans)
            for tenant, items in plans:
                self._serve_tenant(tenant, items)
            self._check_watchdogs()

    def _has_work_locked(self) -> bool:
        return any(
            t.queue and t.status is TenantStatus.ACTIVE
            for t in self._tenants.values()
        )

    def _plan_pass_locked(self):
        """Pop every active tenant's queued items and order the pass:
        control-first (the per-tenant fallback lane — a compute/detach is
        served immediately, never parked behind a signature group), then
        batch tenants grouped by head-batch signature so same-signature
        tenants run back-to-back against the one compiled program they
        share. Popping frees queue capacity, so blocked submitters wake."""
        plans = []
        for t in self._tenants.values():
            if t.queue and t.status is TenantStatus.ACTIVE:
                items = list(t.queue)
                t.queue.clear()
                plans.append((t, items))
                if _obs._enabled:
                    # dequeue-side occupancy sample: the pop empties the
                    # queue while we hold the lock, so an idle-draining
                    # tenant's depth series actually falls to 0 instead of
                    # freezing at the last submit's reading (ISSUE 16 fix)
                    _obs.histo("serve.queue_depth", 0.0, tenant=t.id)
        if not plans:
            return plans
        self._cond.notify_all()
        control, groups = [], {}
        for entry in plans:
            head = entry[1][0]
            if head[0] != "batch":
                control.append(entry)
            else:
                # batch payload is (seq, args); group on the args signature
                groups.setdefault(
                    _batch_signature(head[1][1]), []
                ).append(entry)
        return control + [e for sig in groups for e in groups[sig]]

    def _stage_pass(self, plans) -> None:
        """Coalesced H2D for one serving pass (ISSUE 11): every queued
        host (numpy) batch in ``plans`` transfers in ONE ``device_put``
        per (device, signature) group — not one per batch per tenant —
        and its queue entry is rewritten in place with the placed device
        arrays plus an ``owned`` verdict (exclusively-owned device
        buffers may be donated by the window step; buffers shared via
        identical host arrays may not). Pooled staging buffers release
        here, anchored on a transferred device array, the moment their
        host bytes have been handed to the transfer engine.

        Excluded and left on the per-batch path: tenants under
        ``nan_policy="reject"`` (their priced host-side NaN scan must see
        host memory), non-numpy args (already-placed jax arrays, torch
        tensors, scalars), and metrics without a plain single-device
        placement (sharded placements belong to the SPMD partitioner)."""
        groups: Dict[tuple, list] = {}
        for tenant, items in plans:
            if tenant.nan_policy == "reject":
                continue
            if getattr(tenant.collection, "_host_ingest_only", False):
                # sliced tenants (ISSUE 15): the slice-id column must stay
                # host-side until the collection interns it — a coalesced
                # H2D here would strand the ids on device and force a
                # readback per batch. Slice routing as a staging-pass
                # step is the ROADMAP 3(c) follow-up seam.
                continue
            probe = getattr(tenant.collection, "_defer_probe", None)
            device = getattr(probe, "_plain_device", None)
            if device is None:
                continue
            for i, (kind, payload, _promise) in enumerate(items):
                if kind != "batch":
                    continue
                args = payload[1]
                if not args or not all(
                    type(a) is np.ndarray and a.dtype.kind in "biufc"
                    for a in args
                ):
                    continue
                sig = tuple((a.shape, a.dtype) for a in args)
                groups.setdefault((id(device), sig), []).append(
                    (device, items, i)
                )
        from torcheval_tpu.serve import ingest as _ingest

        for members in groups.values():
            device = members[0][0]
            batches = [items[i][1][1] for _dev, items, i in members]
            try:
                placed, owned = _ingest.coalesce_h2d(batches, device)
            except Exception:  # noqa: BLE001 - fall back to per-batch path
                # an unplaceable group (device trouble) keeps the host
                # arrays; the per-batch update path will surface the real
                # error inside the owning tenant's containment wall
                continue
            for (_dev, items, i), dev_args, own in zip(
                members, placed, owned
            ):
                kind, payload, promise = items[i]
                stage = payload[2] if len(payload) > 2 else None
                items[i] = (
                    kind, (payload[0], dev_args, None, own), promise
                )
                if stage is not None:
                    # host bytes are consumed once THIS batch's transfers
                    # retire — anchor on all of its own placed arrays
                    # (transfers within a batched device_put can complete
                    # independently; anchoring on another batch's array
                    # could recycle the slot mid-read)
                    stage.release(
                        anchor=(
                            dev_args[0]
                            if len(dev_args) == 1
                            else _ingest.group_anchor(dev_args)
                        )
                    )

    def _serve_tenant(self, tenant: _Tenant, items) -> None:
        t0 = time.perf_counter()
        try:
            self._serve_tenant_inner(tenant, items)
        finally:
            self._ewma("step", time.perf_counter() - t0)

    def _serve_tenant_inner(self, tenant: _Tenant, items) -> None:
        with _obs.span("serve.tenant.step", tenant=tenant.id):
            for idx, (kind, payload, promise) in enumerate(items):
                try:
                    if kind == "batch":
                        self._process_batch(tenant, payload)
                    elif kind == "compute":
                        promise.resolve(
                            self._guarded(tenant, tenant.collection.compute)
                        )
                    elif kind == "sync_compute":
                        self._do_sync_compute(tenant, payload, promise)
                    elif kind == "flush":
                        self._do_flush(tenant, promise)
                    elif kind == "detach":
                        self._do_detach(tenant, payload, promise)
                except Exception as exc:  # noqa: BLE001 - containment wall
                    err = self._classify_and_quarantine(tenant, kind, exc)
                    # the rest of this tenant's popped items die with it:
                    # batches drop (their staging buffers release — no
                    # pool leak across a quarantine), promises learn the
                    # structured reason
                    for _k, _p, pr in items[idx:]:
                        self._release_stage(_k, _p)
                        if pr is not None and not pr.event.is_set():
                            pr.reject(err)
                    return
        with self._cond:
            tenant.last_activity = time.monotonic()

    @staticmethod
    def _release_stage(kind: str, payload: Any) -> None:
        """Free a dropped queue entry's pooled staging buffer (idempotent;
        entries the staging pass already placed carry ``stage=None``)."""
        if kind == "batch" and len(payload) > 2 and payload[2] is not None:
            payload[2].release(anchor=_ingest_anchor())

    def _process_batch(self, tenant: _Tenant, payload: tuple) -> None:
        # (seq, args) legacy 2-tuples still appear in tests that inject
        # queue entries directly; the full form is (seq, args, stage,
        # owned) — ``owned`` non-None means the staging pass already
        # placed ``args`` on device (and vouches for buffer ownership)
        seq, args = payload[0], payload[1]
        stage = payload[2] if len(payload) > 2 else None
        owned = payload[3] if len(payload) > 3 else None
        release_anchor = None
        try:
            if tenant.nan_policy == "reject":
                self._nan_check(tenant, args)
            if owned is None and stage is not None:
                # stage-backed host views that skipped the staging pass
                # (nan-reject tenants, fallback): place them HERE so the
                # stage's release anchors on exactly the transfers that
                # read the pooled bytes — an unrelated anchor (or none)
                # could recycle the slot mid-read on async-H2D backends
                placed = self._place_batch(tenant, args)
                if placed is not None:
                    args, release_anchor, owned = placed
                else:
                    # no plain device to anchor a transfer on (sharded
                    # placements, exotic args): materialize the views
                    # once so the slot can free with zero aliasing risk
                    args = tuple(
                        np.array(a) if isinstance(a, np.ndarray) else a
                        for a in args
                    )
            if owned is None:
                self._guarded(
                    tenant, lambda: tenant.collection.update(*args)
                )
            else:
                self._guarded(
                    tenant,
                    lambda: tenant.collection.update_placed(
                        args, owned=owned
                    ),
                )
        finally:
            if stage is not None:
                # release_anchor covers the staged-placement case; every
                # other path above either materialized the views (no
                # aliasing left) or never read the stage (early raise)
                stage.release(anchor=release_anchor)
        tenant.processed += 1
        if seq is not None:
            # worker-thread-only write: the applied watermark is what a
            # checkpoint taken on this thread can truthfully claim. The
            # per-tenant queue is FIFO so seqs arrive ascending; max() is
            # armor against any future scheduler reordering quietly
            # regressing the watermark below an applied seq
            tenant.applied_seq = max(tenant.applied_seq, seq)

    @staticmethod
    def _place_batch(tenant: _Tenant, args: tuple):
        """Device-place one stage-backed host batch through the ingest
        transfer machinery; returns ``(placed_args, anchor, owned)`` or
        ``None`` when the batch is not eligible (mirrors the staging
        pass's gates)."""
        probe = getattr(tenant.collection, "_defer_probe", None)
        device = getattr(probe, "_plain_device", None)
        if (
            device is None
            or getattr(tenant.collection, "_host_ingest_only", False)
            or not args
            or not all(
                type(a) is np.ndarray and a.dtype.kind in "biufc"
                for a in args
            )
        ):
            return None
        from torcheval_tpu.serve import ingest as _ingest

        try:
            placed, owned = _ingest.coalesce_h2d([args], device)
        except Exception:  # noqa: BLE001 - keep the host-path fallback
            return None
        dev_args = placed[0]
        anchor = (
            dev_args[0]
            if len(dev_args) == 1
            else _ingest.group_anchor(dev_args)
        )
        # owned[0] is False only when one host array appeared twice in
        # the batch (its device twin is shared — donating it twice would
        # be a duplicate-donation error)
        return dev_args, anchor, owned[0]

    @staticmethod
    def _nan_check(tenant: _Tenant, args: tuple) -> None:
        for a in args:
            try:
                arr = np.asarray(a)
            except Exception:
                continue
            if arr.dtype.kind == "f" and bool(np.isnan(arr).any()):
                raise _NaNPolicyViolation(
                    f"tenant {tenant.id!r} submitted a float batch "
                    "containing NaN under nan_policy='reject'."
                )

    def _guarded(self, tenant: _Tenant, fn):
        """Run one tenant device step under its PR 5 watchdog deadline
        (``toolkit._sync_deadline`` + ``_run_guarded`` — the exact
        machinery the sync APIs use). ``None`` = unguarded (the default;
        guarding costs one thread per step)."""
        if tenant.step_timeout_s is None:
            return fn()
        from torcheval_tpu.metrics import toolkit as tk

        with tk._sync_deadline(tenant.step_timeout_s):
            return tk._run_guarded(fn, "serve.step", "serve")

    def _do_sync_compute(
        self, tenant: _Tenant, payload: dict, promise: _Promise
    ) -> None:
        """Cross-rank sync of one tenant's metrics on the worker thread.
        A SyncError here is the CLIENT's to handle (it chose timeout_s /
        on_failure) and the tenant's local state is untouched by a failed
        exchange — so sync failures reject the promise without
        quarantining."""
        from torcheval_tpu.metrics import toolkit as tk

        try:
            promise.resolve(
                tk.sync_and_compute_collection(
                    dict(tenant.collection.metrics),
                    recipient_rank="all",
                    timeout_s=payload["timeout_s"],
                    on_failure=payload["on_failure"],
                )
            )
        except tk.SyncError as exc:
            promise.reject(exc)

    def _do_detach(
        self, tenant: _Tenant, payload: dict, promise: _Promise
    ) -> None:
        """Graceful detach / explicit eviction, on the worker: optionally
        fold+checkpoint, then free the slot. A checkpoint failure (disk
        full, schema surprise) rejects the promise and leaves the tenant
        ACTIVE — environmental errors are not tenant poison."""
        path = None
        try:
            if payload["checkpoint"]:
                path = self._checkpoint_tenant(tenant)
                tenant.durable_seq = tenant.applied_seq
        except Exception as exc:  # noqa: BLE001 - relayed to the caller
            promise.reject(exc)
            return
        evict = payload["evict"]
        with self._cond:
            if evict:
                tenant.status = TenantStatus.EVICTED
                tenant.error = TenantEvictedError(
                    "evicted",
                    f"tenant {tenant.id!r} was evicted; resume from "
                    f"{path!r}.",
                    tenant=tenant.id,
                    checkpoint=path,
                )
                self._totals["evicted"] += 1
            else:
                tenant.status = TenantStatus.DETACHED
            self._tenants.pop(tenant.id, None)
            if _obs._enabled:
                _obs.gauge("serve.tenants.active", float(len(self._tenants)))
        if evict and _obs._enabled:
            _obs.counter(
                "serve.evictions", tenant=tenant.id, reason="explicit"
            )
        promise.resolve(path)

    def _do_flush(self, tenant: _Tenant, promise: _Promise) -> None:
        """Checkpoint the tenant's current folded state WITHOUT evicting
        it — the wire client's replay-buffer valve: a flush advances the
        durable watermark so the client can prune acked-and-now-durable
        batches from its bounded replay buffer. An environmental
        checkpoint failure rejects the promise and leaves the tenant
        ACTIVE (same contract as detach — disk trouble is not tenant
        poison)."""
        try:
            path = self._checkpoint_tenant(tenant)
        except Exception as exc:  # noqa: BLE001 - relayed to the caller
            promise.reject(exc)
            return
        tenant.durable_seq = tenant.applied_seq
        promise.resolve({"path": path, "acked_seq": tenant.durable_seq})

    def _checkpoint_tenant(self, tenant: _Tenant, *, rotate: bool = True) -> str:
        from torcheval_tpu.resilience.snapshot import save

        ckpt_dir = self._tenant_ckpt_dir(tenant.id, create=True)
        # worker thread: every queued batch ahead of this request has been
        # applied, so applied_seq is exactly the set of batches the folded
        # state (and therefore this checkpoint) contains. The watermark
        # rides the manifest's ``extra`` through the same atomic publish.
        # NOTE: callers commit ``tenant.durable_seq`` themselves AFTER the
        # checkpoint is known to stick — the idle-eviction path can still
        # DISCARD this checkpoint if a submit raced in, and a watermark
        # advanced for a discarded checkpoint would let a client prune
        # replay entries whose only durable copy was just deleted.
        # ``rotate=False`` defers keep_last rotation for the same reason:
        # rotating at save time and then discarding the new checkpoint
        # could leave ZERO checkpoints behind (with keep_last=1 the save
        # deletes the old durable one and the abort deletes the new one)
        # — the idle path rotates only after its eviction commits.
        with _obs.span("serve.tenant.evict", tenant=tenant.id):
            return save(
                tenant.collection,
                ckpt_dir,
                keep_last=self._evict_keep_last if rotate else None,
                extra={"serve": {"acked_seq": tenant.applied_seq}},
            )

    def _rotate_tenant_ckpts(self, tenant_id: str) -> None:
        """Apply ``evict_keep_last`` rotation after a deferred-rotation
        checkpoint COMMITTED (see ``_checkpoint_tenant(rotate=False)``)."""
        from torcheval_tpu.resilience.snapshot import rotate_checkpoints

        ckpt_dir = self._tenant_ckpt_dir(tenant_id, create=False)
        if ckpt_dir is None or self._evict_keep_last is None:
            return
        rotate_checkpoints(ckpt_dir, self._evict_keep_last)

    def _classify_and_quarantine(
        self, tenant: _Tenant, kind: str, exc: Exception
    ) -> TenantQuarantinedError:
        from torcheval_tpu.metrics import toolkit as tk

        if isinstance(exc, _NaNPolicyViolation):
            reason = "nan_policy"
        elif isinstance(exc, tk.SyncTimeoutError):
            reason = "step_timeout"
        elif kind == "batch":
            reason = "poisoned_batch"
        else:
            reason = "compute_error"
        err = TenantQuarantinedError(
            reason,
            f"tenant {tenant.id!r} quarantined: {exc!r}. Other tenants are "
            "unaffected; detach and re-attach to start clean.",
            tenant=tenant.id,
        )
        err.__cause__ = exc
        with self._cond:
            tenant.status = TenantStatus.QUARANTINED
            tenant.error = err
            # anything still queued dies with the tenant: batches drop
            # (and release their staging buffers — a quarantine must not
            # leak pool slots), waiting promises learn the reason
            for _k, _p, pr in tenant.queue:
                self._release_stage(_k, _p)
                if pr is not None and not pr.event.is_set():
                    pr.reject(err)
            tenant.queue.clear()
            self._totals["quarantined"] += 1
            self._cond.notify_all()
        _logger.warning(
            "serve: quarantined tenant %r (%s): %r", tenant.id, reason, exc
        )
        if _obs._enabled:
            _obs.counter("serve.quarantines", tenant=tenant.id, reason=reason)
            _trace.instant(
                "serve.tenant.quarantined",
                kind="serve",
                tenant=tenant.id,
                reason=reason,
            )
        return err

    def _check_watchdogs(self) -> None:
        now = time.monotonic()
        victims = []
        with self._cond:
            for t in self._tenants.values():
                if (
                    t.status is TenantStatus.ACTIVE
                    and t.watchdog_timeout_s is not None
                    and not t.queue
                    and now - t.last_activity >= t.watchdog_timeout_s
                ):
                    victims.append(t)
        for t in victims:
            self._evict_idle(t)

    def _evict_idle(self, tenant: _Tenant) -> None:
        """Watchdog eviction of an idle (stuck-producer) tenant: fold +
        checkpoint, then free the slot. The save runs on the worker thread
        OUTSIDE the daemon lock (holding it across a fold + fsync would
        stall every tenant's submit for the save's duration); it is safe
        unlocked because only this thread ever touches the collection. The
        eviction then commits under the lock ONLY if the tenant is still
        idle — a submit that raced in during the save means the tenant is
        live (and the checkpoint stale), so the eviction aborts and the
        just-published checkpoint is discarded (a mid-stream snapshot left
        behind would become a wrong resume source for a later
        ``resume="auto"`` attach)."""
        with self._cond:
            if (
                tenant.status is not TenantStatus.ACTIVE
                or tenant.queue
                or self._tenants.get(tenant.id) is not tenant
            ):
                return  # a submit raced the watchdog: the tenant is live
        try:
            # rotation deferred to the commit below: if the eviction
            # aborts, the discarded checkpoint must not have rotated away
            # the previous durable one (clients pruned replay buffers
            # against its watermark)
            path = self._checkpoint_tenant(tenant, rotate=False)
        except Exception as exc:  # noqa: BLE001 - never kill the worker
            _logger.warning(
                "serve: idle eviction of %r failed to checkpoint (%r); "
                "leaving the tenant attached.",
                tenant.id,
                exc,
            )
            return
        with self._cond:
            if (
                tenant.status is not TenantStatus.ACTIVE
                or tenant.queue
                or self._tenants.get(tenant.id) is not tenant
            ):
                # activity landed during the save: abort and discard the
                # now-stale checkpoint (only this thread consumes queues,
                # so ANY new work is visible here as a non-empty queue;
                # durable_seq was never advanced for it, so no client has
                # pruned replay entries against the discarded copy)
                shutil.rmtree(path, ignore_errors=True)
                return
            tenant.durable_seq = tenant.applied_seq
            tenant.status = TenantStatus.EVICTED
            tenant.error = TenantEvictedError(
                "watchdog_idle",
                f"tenant {tenant.id!r} idle past its watchdog deadline "
                f"({tenant.watchdog_timeout_s}s) was evicted; resume from "
                f"{path!r}.",
                tenant=tenant.id,
                checkpoint=path,
            )
            self._tenants.pop(tenant.id, None)
            self._totals["evicted"] += 1
            if _obs._enabled:
                _obs.gauge("serve.tenants.active", float(len(self._tenants)))
        self._rotate_tenant_ckpts(tenant.id)
        _logger.warning(
            "serve: evicted idle tenant %r (checkpoint %s)", tenant.id, path
        )
        if _obs._enabled:
            _obs.counter(
                "serve.evictions", tenant=tenant.id, reason="watchdog_idle"
            )
            _trace.instant(
                "serve.tenant.evicted",
                kind="serve",
                tenant=tenant.id,
                reason="watchdog_idle",
            )

    def _fail_pending_locked(self) -> None:
        err = ServeError("daemon_stopped", "the daemon has been stopped.")
        for t in self._tenants.values():
            for _k, _p, pr in t.queue:
                self._release_stage(_k, _p)
                if pr is not None and not pr.event.is_set():
                    pr.reject(err)
            t.queue.clear()

    # --------------------------------------------------------------- health
    _LOAD_REPORT_SCHEMA = 1

    def load_report(self) -> Dict[str, Any]:
        """Structured, schema-versioned load telemetry for this host —
        the unit the obs push channel labels into every delta, ``health()``
        embeds, the ``/health`` scrape endpoint serves, and
        ``EvalRouter.fleet_status()`` folds per host (the signal layer
        ROADMAP item 1's placement loop consumes).

        Top-level keys are STABLE under ``schema == 1`` (pinned by
        ``tests/serve/test_load_report.py``); additions bump the schema::

            {"schema": 1, "ts": ..., "uptime_s": ..., "running": ...,
             "draining": ..., "capacity": {...}, "queue": {...},
             "latency": {...}, "window": {...}, "ingest": {...},
             "hbm": {...}, "totals": {...}}

        Latency p99s fold the registry's ``serve.submit.latency``
        histograms / ``serve.tenant.step`` span buckets across tenants
        (bucket summation — exact); EWMAs are the daemon's own running
        aggregates; HBM folds the ``obs.cost.hbm_bytes{entry=}`` gauges.
        When obs is disabled the registry-derived fields read 0 — the
        queue/capacity/totals fields are daemon-native and always live."""
        now = time.monotonic()
        with self._cond:
            per_tenant = {
                t.id: len(t.queue) for t in self._tenants.values()
            }
            backlog = 0
            for t in self._tenants.values():
                for kind, payload, _p in t.queue:
                    if kind == "batch":
                        for a in payload[1] or ():
                            backlog += int(getattr(a, "nbytes", 0) or 0)
            out: Dict[str, Any] = {
                "schema": self._LOAD_REPORT_SCHEMA,
                "ts": time.time(),
                "uptime_s": (
                    now - self._started_at if self._started_at else 0.0
                ),
                "running": self._running,
                "draining": self._draining,
                "capacity": {
                    "max_tenants": self._max_tenants,
                    "active_tenants": len(self._tenants),
                },
                "queue": {
                    "depth": sum(per_tenant.values()),
                    "capacity": sum(
                        t.capacity for t in self._tenants.values()
                    ),
                    "per_tenant": per_tenant,
                },
                "ingest": {"backlog_bytes": backlog},
                "totals": dict(self._totals),
            }
            ewma = dict(self._lat_ewma)
        # registry folds OUTSIDE the daemon lock (the registry has its own)
        from torcheval_tpu.obs.registry import (
            HISTOGRAM_BUCKETS,
            default_registry,
            percentile_from_buckets,
        )

        submit_b = [0] * HISTOGRAM_BUCKETS
        submit_c = 0
        step_b = [0] * HISTOGRAM_BUCKETS
        step_c = 0
        occ_sum, occ_c = 0.0, 0
        hbm_max, hbm_sum = 0.0, 0.0
        for kind, name, _lb, value in default_registry._items():
            if kind == "histo" and name == "serve.submit.latency":
                for i, c in enumerate(value[0]):
                    submit_b[i] += c
                submit_c += value[1]
            elif kind == "span" and name == "serve.tenant.step":
                for i, c in enumerate(value[3]):
                    step_b[i] += c
                step_c += value[0]
            elif kind == "histo" and name == "deferred.window_occupancy":
                occ_sum += value[2]
                occ_c += value[1]
            elif kind == "gauge" and name == "obs.cost.hbm_bytes":
                hbm_max = max(hbm_max, value)
                hbm_sum += value
        out["latency"] = {
            "submit_ewma_s": ewma.get("submit", 0.0),
            "step_ewma_s": ewma.get("step", 0.0),
            "submit_p99_s": percentile_from_buckets(
                submit_b, submit_c, 0.99
            ),
            "step_p99_s": percentile_from_buckets(step_b, step_c, 0.99),
        }
        out["window"] = {
            "occupancy_mean": occ_sum / occ_c if occ_c else 0.0,
            "samples": occ_c,
        }
        out["hbm"] = {
            "bytes_max_entry": hbm_max,
            "bytes_sum": hbm_sum,
        }
        return out

    def list_tenants(self) -> Dict[str, Dict[str, Any]]:
        """The tenant directory a recovering control plane reconciles
        against (ISSUE 20): every attached tenant's status and seq
        watermarks, one cheap read under the daemon lock. ``last_seq`` is
        the highest wire sequence this daemon has admitted (a restarted
        router resumes its client-side numbering from here);
        ``durable_seq`` is the checkpointed watermark. Served over the
        wire as the ``list_tenants`` op."""
        with self._cond:
            return {
                t.id: {
                    "status": t.status.value,
                    "last_seq": t.last_seq,
                    "durable_seq": t.durable_seq,
                }
                for t in self._tenants.values()
            }

    def health(
        self,
        *,
        sync: bool = False,
        timeout_s: Optional[float] = None,
        on_failure: str = "raise",
    ) -> Dict[str, Any]:
        """Structured daemon health snapshot: per-tenant status, queue
        depth, ingest/shed totals and idle age, plus daemon capacity and
        lifetime counts. With ``sync=True`` the snapshot also carries
        ``"cluster"`` — every rank's obs registry/timeline merged over
        ``obs.sync_snapshot()``'s single collective round, under the PR 5
        ``timeout_s``/``on_failure`` contract (a monitoring loop keeps
        reporting through a preemption with ``on_failure="local"``)."""
        now = time.monotonic()
        with self._cond:
            tenants = {
                t.id: {
                    "status": t.status.value,
                    "queue_depth": len(t.queue),
                    "queue_capacity": t.capacity,
                    "ingested": t.ingested,
                    "processed": t.processed,
                    "sheds": t.sheds,
                    "dupes": t.dupes,
                    "last_seq": t.last_seq,
                    "applied_seq": t.applied_seq,
                    "durable_seq": t.durable_seq,
                    "idle_s": now - t.last_activity,
                }
                for t in self._tenants.values()
            }
            out: Dict[str, Any] = {
                "running": self._running,
                "draining": self._draining,
                "worker_alive": (
                    self._thread.is_alive() if self._thread else False
                ),
                "uptime_s": (
                    now - self._started_at if self._started_at else 0.0
                ),
                "capacity": {
                    "max_tenants": self._max_tenants,
                    "active_tenants": len(self._tenants),
                },
                "totals": dict(self._totals),
                "tenants": tenants,
            }
        # outside the lock: load_report() re-acquires it (and the old-peer
        # fallback path reads this — a subscriber polling health() still
        # sees the same structured load telemetry a push would carry)
        out["load_report"] = self.load_report()
        if sync:
            from torcheval_tpu import obs

            out["cluster"] = obs.sync_snapshot(
                timeout_s=timeout_s, on_failure=on_failure
            )
        return out

    def __repr__(self) -> str:
        with self._lock:
            n = len(self._tenants)
        state = "running" if self._running else "stopped"
        return f"EvalDaemon({state}, tenants={n}/{self._max_tenants})"

"""Pooled host staging buffers + coalesced H2D for the eval service.

ISSUE 11's ingest pipeline. The cluster path used to decode each frame
into fresh host numpy (two copies per leaf), device-put each batch on its
own, and do all of it serially with the window step. This module supplies
the two host-side stages that turn that into a pipeline:

* :class:`HostBufferPool` — size-classed, reusable host staging buffers.
  ``recv_frame_into`` reads each frame's payload straight into a pooled
  slot and ``unpack_tree`` decodes zero-copy views over it
  (``utils/npz.py``), so the steady ingest path performs no per-batch
  payload allocation at all. The **aliasing contract**: a released buffer
  is not recycled while anything that read it may still be in flight —
  ``release(anchor=...)`` parks the slot in a cooling rack keyed by an
  execution/transfer anchor (a ``jax.Array`` — the PR 6 donated-hold
  registry's anchor discipline) and the slot only re-enters the free list
  once ``anchor.is_ready()``. An anchor whose probe *raises* was donated
  into a later program; same-device programs retire in submission order
  and an H2D read always completes before the program consuming it runs,
  so a deleted anchor proves the host read is over and the slot is safe.
* :func:`coalesce_h2d` — ONE ``jax.device_put`` call per coalesced
  signature group per serving pass (the daemon's scheduler builds the
  groups), instead of one transfer per batch per tenant. Identical host
  arrays (by object identity) transfer once and share one device buffer —
  the 100-tenants-one-signature win from PR 8 extended from compile time
  to transfer count. Shared device buffers are reported back so the
  caller can demote ``owned`` (a shared chunk must never be donated).

Observability: ``serve.ingest.pool{result=hit|miss|grow}`` counters on
every acquire, a ``serve.ingest.h2d_bytes`` counter and one
``serve.ingest.transfer`` timeline bar per coalesced transfer, and a
``serve.ingest.stage`` bar per pooled payload fill (emitted by the wire).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _trace

__all__ = ["HostBufferPool", "PooledBuffer", "SharedStage", "coalesce_h2d"]

_MIN_CLASS_BITS = 12  # smallest slot: 4 KiB


def _size_class(nbytes: int) -> int:
    bits = max(int(nbytes - 1).bit_length(), _MIN_CLASS_BITS)
    return 1 << bits


class PooledBuffer:
    """One staging slot handed out by :class:`HostBufferPool`.

    ``view(n)`` exposes the first ``n`` bytes as a writable memoryview
    (the ``recv_into`` target and the npz-view backing store).
    ``release(anchor=...)`` hands the slot back; it is idempotent — the
    first call wins, later calls are no-ops — so the ownership handoff
    between the wire handler and the daemon worker can be belt-and-braces
    on error paths without double-freeing."""

    __slots__ = ("pool", "nbytes", "data", "_released", "_split")

    def __init__(self, pool: "HostBufferPool", nbytes: int) -> None:
        self.pool = pool
        self.nbytes = nbytes  # size class, not the payload length
        self.data = np.empty(nbytes, dtype=np.uint8)
        self._released = False
        self._split = False

    def view(self, n: int) -> memoryview:
        return memoryview(self.data)[:n]

    def release(self, *, anchor: Any = None) -> None:
        if self._released or self._split:
            # _split: ownership moved to a SharedStage's holders — only
            # the LAST share may free the slot, via _release_from_split
            # (a direct release here is the wire's belt-and-braces error
            # path firing late, and must never bypass the shares'
            # accumulated anchors)
            return
        self._released = True
        self.pool._release(self, anchor)

    def _release_from_split(self, anchor: Any) -> None:
        """The SharedStage-only release: frees the slot regardless of the
        ``_split`` latch (which stays set until the pool recycles the
        slot, so a racing direct ``release()`` can never free it with the
        shares' anchors discarded)."""
        if self._released:
            return
        self._released = True
        self.pool._release(self, anchor)

    @property
    def released(self) -> bool:
        return self._released


class _GroupAnchor:
    """Composite anchor: retired only when EVERY member anchor is."""

    __slots__ = ("anchors",)

    def __init__(self, anchors: List[Any]) -> None:
        self.anchors = anchors

    def is_ready(self) -> bool:
        return all(_anchor_retired(a) for a in self.anchors)


def group_anchor(anchors) -> _GroupAnchor:
    """An anchor that retires only when every anchor in ``anchors`` has."""
    return _GroupAnchor(list(anchors))


class SharedStage:
    """Reference-shared ownership of one :class:`PooledBuffer` backing
    SEVERAL queued batches (the coalesced ``submit_many`` frame): each
    holder's ``release`` drops one share and contributes its anchor; the
    slot frees when the last share goes, guarded by ALL contributed
    anchors (one frame's batches can ride different coalesced transfers
    — the earliest-released group's transfer may still be in flight when
    the last share drops). Individual releases stay idempotent-per-holder
    by the daemon's one-release-per-queue-entry discipline."""

    __slots__ = ("_stage", "_lock", "_n", "_anchors")

    def __init__(self, stage: PooledBuffer, n: int) -> None:
        self._stage = stage
        self._lock = threading.Lock()
        self._n = n
        self._anchors: List[Any] = []
        stage._split = True

    def release(self, *, anchor: Any = None) -> None:
        with self._lock:
            if anchor is not None:
                self._anchors.append(anchor)
            self._n -= 1
            if self._n != 0:
                return
            anchors = self._anchors
        final = (
            None
            if not anchors
            else anchors[0] if len(anchors) == 1 else _GroupAnchor(anchors)
        )
        # _split stays latched: a concurrent direct release() between a
        # cleared latch and this call would free the slot with the
        # accumulated anchors discarded
        self._stage._release_from_split(final)

    @property
    def released(self) -> bool:
        return self._stage.released


def _anchor_retired(anchor: Any) -> bool:
    """True when ``anchor``'s transfer/program can no longer read host
    memory. A raised probe means the anchor was donated to a later
    program — by then its own execution (and therefore every host read
    feeding it) has been sequenced, so the slot is safe (module doc)."""
    if anchor is None:
        return True
    try:
        return bool(anchor.is_ready())
    except Exception:
        return True


class HostBufferPool:
    """Size-classed reusable host staging buffers (module doc).

    ``max_slots_per_class`` bounds the FREE list per class (in-flight and
    cooling slots are unbounded — backpressure for those is the daemon's
    queue bound, not the pool's); ``idle_ttl_s`` drops free slots that
    have not been reused for that long, so a burst does not pin its peak
    footprint forever (:meth:`shrink` runs opportunistically on acquire).
    Thread-safe: wire handler threads acquire, the daemon worker releases.
    """

    def __init__(
        self, *, max_slots_per_class: int = 8, idle_ttl_s: float = 30.0
    ) -> None:
        self._lock = threading.Lock()
        # size class -> [(buffer, freed_at)] free slots, LIFO for warmth
        self._free: Dict[int, List[Tuple[PooledBuffer, float]]] = {}
        # [(buffer, anchor)] released slots whose reader may be in flight
        self._cooling: List[Tuple[PooledBuffer, Any]] = []
        self._max_slots = max_slots_per_class
        self._idle_ttl_s = idle_ttl_s
        self._last_shrink = 0.0
        self.allocated = 0  # lifetime allocations (tests/ops visibility)

    def acquire(self, nbytes: int) -> PooledBuffer:
        """A staging slot of at least ``nbytes``. Recycles a retired slot
        when one exists (``result=hit``); otherwise allocates — counted as
        ``grow`` when slots of the class exist but are all still in
        flight (the double-buffering case: window N holds the pool's
        warm slot, window N+1 must come from a fresh one), ``miss`` on
        first sight of the class."""
        cls = _size_class(nbytes)
        now = time.monotonic()
        with self._lock:
            self._sweep_cooling_locked()
            free = self._free.get(cls)
            if free:
                buf, _t = free.pop()
                buf._released = False
                buf._split = False  # the split latch dies with the cycle
                result = "hit"
            else:
                in_flight = any(
                    b.nbytes == cls for b, _a in self._cooling
                )
                result = "grow" if in_flight else "miss"
                buf = PooledBuffer(self, cls)
                self.allocated += 1
            if now - self._last_shrink >= 1.0:
                self._last_shrink = now
                self._shrink_locked(now)
        if _obs._enabled:
            _obs.counter("serve.ingest.pool", result=result)
        return buf

    def _release(self, buf: PooledBuffer, anchor: Any) -> None:
        with self._lock:
            if anchor is not None and not _anchor_retired(anchor):
                self._cooling.append((buf, anchor))
                return
            self._free_locked(buf, time.monotonic())

    def _free_locked(self, buf: PooledBuffer, now: float) -> None:
        free = self._free.setdefault(buf.nbytes, [])
        if len(free) < self._max_slots:
            free.append((buf, now))
        # over the cap: drop the buffer on the floor (plain GC)

    def _sweep_cooling_locked(self) -> None:
        if not self._cooling:
            return
        now = time.monotonic()
        still = []
        for buf, anchor in self._cooling:
            if _anchor_retired(anchor):
                self._free_locked(buf, now)
            else:
                still.append((buf, anchor))
        self._cooling = still

    def _shrink_locked(self, now: float) -> None:
        for cls, free in list(self._free.items()):
            kept = [
                (b, t) for b, t in free if now - t < self._idle_ttl_s
            ]
            if kept:
                self._free[cls] = kept
            else:
                del self._free[cls]

    def shrink(self, *, now: Optional[float] = None) -> None:
        """Drop free slots idle past ``idle_ttl_s`` (also runs
        opportunistically on acquire, at most once a second)."""
        with self._lock:
            self._sweep_cooling_locked()
            self._shrink_locked(
                time.monotonic() if now is None else now
            )

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "free": sum(len(v) for v in self._free.values()),
                "cooling": len(self._cooling),
                "allocated": self.allocated,
            }


def coalesce_h2d(
    batches: Sequence[Tuple[np.ndarray, ...]],
    device: Any = None,
) -> Tuple[List[Tuple[Any, ...]], List[bool]]:
    """Transfer every host batch in ``batches`` (tuples of numpy arrays,
    one signature group) in ONE ``jax.device_put`` call. Returns
    ``(placed_batches, owned_flags)``: per input batch, the device-array
    tuple and whether every one of its device buffers is exclusively that
    batch's (identical host arrays transfer once and share one device
    buffer — such a batch reports ``owned=False`` so its chunks are never
    donated)."""
    import jax

    unique: Dict[int, int] = {}
    uses: Dict[int, int] = {}
    order: List[np.ndarray] = []
    for args in batches:
        for a in args:
            key = id(a)
            if key not in unique:
                unique[key] = len(order)
                order.append(a)
            uses[key] = uses.get(key, 0) + 1
    t0 = time.perf_counter()
    placed = (
        jax.device_put(order, device) if device is not None
        else jax.device_put(order)
    )
    nbytes = sum(int(a.nbytes) for a in order)
    if _obs._enabled:
        _obs.counter("serve.ingest.h2d_bytes", float(nbytes))
        _trace.complete(
            "serve.ingest.transfer",
            t0,
            time.perf_counter() - t0,
            kind="serve",
            bytes=nbytes,
            arrays=len(order),
            batches=len(batches),
        )
    out: List[Tuple[Any, ...]] = []
    owned: List[bool] = []
    for args in batches:
        out.append(tuple(placed[unique[id(a)]] for a in args))
        owned.append(all(uses[id(a)] == 1 for a in args))
    return out, owned

"""Network wire for the eval service: framing, marshalling, `EvalServer`.

ISSUE 10's ingestion layer. The single-host :class:`EvalDaemon` (PR 8)
already decouples many producer *threads* from one device-owning worker;
this module pushes the producer side across a network boundary — the
Podracer split of many remote actors feeding a small number of
device-owning learners (arXiv:2104.06272) — with **no new runtime
dependency**: plain TCP sockets, a length-prefixed JSON header, and an
optional ``npz`` binary payload for arrays.

Frame layout (all integers big-endian)::

    magic   4 bytes  b"TEW1"   (protocol + version; a stray speaker on
                                the port fails fast as "protocol")
    hlen    4 bytes  uint32    header length
    plen    8 bytes  uint64    payload length
    header  hlen bytes         UTF-8 JSON object
    payload plen bytes         npz archive (absent when plen == 0)

Request headers carry ``op`` (``attach`` / ``submit`` / ``compute`` /
``sync_compute`` / ``flush`` / ``detach`` / ``drain`` / ``health`` /
``snapshot`` / ``subscribe_obs``) plus op-specific fields; responses
carry ``ok`` and either
the result or a structured ``error`` object that reconstructs the
serve-side exception CLASS, ``reason``, and ``retryable`` flag on the
client (:func:`encode_error` / :func:`decode_error`) — a remote caller
branches on exactly the bits a local caller would.

Array trees (submit args, compute results) cross as
:func:`pack_tree`/:func:`unpack_tree`: a JSON spec mirroring the
container structure with array leaves swapped for indices into one npz
payload — exact dtype/shape round trip, no pickling, ``allow_pickle``
stays off.

**Exactly-once submits.** Each wire submit carries the client's
per-tenant monotonic ``seq``; the daemon deduplicates at admission
(``seq <= last admitted`` is acknowledged without re-applying). The wire
is therefore at-least-once — a client MAY blindly resend after an
ambiguous failure (connection died after send, before the ack) — while
the metric state is exactly-once. Acks return the tenant's *durable*
watermark (highest seq covered by a published checkpoint) so clients can
prune their bounded replay buffers.

**Obs push channel (ISSUE 16).** ``subscribe_obs`` flips a connection
from request-response to server-push: after the ``ok`` ack, a
per-subscription :class:`_ObsPublisher` thread owns the socket and ships
``obs_push`` frames on an ``interval_s`` timer — each carrying the
registry's delta-since-cursor (``obs/stream.py``, O(changed) bytes), the
timeline events since the cursor, and the daemon's structured
``load_report()``. Pure TCP: zero collective rounds, ever. A final flush
rides the daemon's ``drain()``/``stop()`` hooks so the last delta
(including the drain's own counters) reaches subscribers before the
socket dies. An OLD server rejects the unknown op structurally
(``WireError("protocol")``) and the subscriber degrades to polling
``health()`` — mixed versions degrade, never break (the PR 12
discipline). Slow subscribers are bounded by the socket send buffer
plus a send timeout: a push that cannot be written in time is dropped
WITH the subscriber (counted in ``obs.stream.dropped``) — a wedged
scraper can never grow daemon-side memory or block a drain.

**Deferred-ack pipelining + local transport (ISSUE 18).** A client that
negotiated a pipeline window at attach opens a dedicated channel with
``pipeline_open``; the ack flips that connection to deferred-ack service
(:meth:`EvalServer._serve_pipelined`): the connection's reader thread
keeps draining frames into a bounded queue while a writer thread
dispatches them and ships acks as batches commit — up to the granted
``depth`` submit frames ride the wire un-acked, each ack echoing the
frame's ``tenant`` + ``seq``/``seqs`` plus the durable watermark.
Lock-step request-response is unchanged and remains the path for every
non-submit op. Same-process clients skip sockets entirely:
:meth:`EvalServer.local_request` hands the payload across as host
memory (the staging-pool slot IS the buffer the daemon decodes — see
the method doc for the aliasing contract).
"""

from __future__ import annotations

import io
import json
import logging
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.resilience import chaos as _chaos
from torcheval_tpu.serve.errors import (
    AdmissionError,
    ServeError,
    WireError,
)
from torcheval_tpu.utils import quant as _quant
from torcheval_tpu.utils.npz import NPZ_FORMAT_ERRORS, npz_views

_logger = logging.getLogger(__name__)

__all__ = [
    "EvalServer",
    "WIRE_CODECS",
    "pack_tree",
    "pack_tree_parts",
    "unpack_tree",
    "encode_error",
    "decode_error",
    "send_frame",
    "send_frame_parts",
    "recv_frame",
    "recv_frame_into",
]

# ------------------------------------------------------------- wire codecs
# Negotiated payload codecs (ISSUE 12). The raw wire ships every array
# leaf verbatim inside the npz payload; a negotiated codec re-encodes
# leaves at pack time, with the decode recipe carried IN THE TREE SPEC —
# so the receiver needs no per-connection state and a frame is always
# self-describing:
#
#   "delta"  delta + min-offset narrowed integer leaves (LOSSLESS —
#            results stay bit-identical; int64 label streams narrow ~8x)
#   "qblk"   everything "delta" does, plus f32 leaves >= 64 elements
#            block-quantized to int8 with per-block f32 scales (bounded
#            error: each element within max|block|/254 — utils/quant.py).
#            An explicit opt-in: score batches decode to *dequantized*
#            values, so downstream metric values carry the documented
#            drift
#
# Negotiation is a capability exchange at ``attach``: the client offers
# ``codecs=[...]`` in the attach header, the server answers with its
# pick, and only then does the client encode — an old server ignores the
# unknown field and answers without one, an old client never offers, and
# either way both sides land on raw with no protocol error (the
# mixed-version interop contract, tested in tests/serve/test_wire_codec.py).
# Every encoder falls back to a raw leaf when encoding would not shrink
# it, so a codec can only reduce payload bytes.
WIRE_CODECS = ("qblk", "delta")

_MAGIC = b"TEW1"
_HEAD = struct.Struct(">4sIQ")
_MAX_HEADER_BYTES = 16 << 20
_MAX_PAYLOAD_BYTES = 1 << 31

# ---------------------------------------------------------- local transport
# Same-process server registry (ISSUE 18): an EvalServer registers its
# endpoint at bind time so an EvalClient constructed in the SAME process
# can hand request payloads across as host memory (EvalServer.local_request)
# instead of copying them through the loopback socket. Registration is
# keyed by the exact "host:port" endpoint string the client dials, and a
# closed server deregisters — a client that finds nothing here (or races
# a close) simply speaks TCP, byte-identical.
_LOCAL_SERVERS: Dict[str, "EvalServer"] = {}
_LOCAL_SERVERS_LOCK = threading.Lock()


def local_server(endpoint: str) -> Optional["EvalServer"]:
    """The same-process :class:`EvalServer` bound at ``endpoint``, or
    ``None`` — the client's per-request gate for the shared-memory local
    transport."""
    with _LOCAL_SERVERS_LOCK:
        return _LOCAL_SERVERS.get(endpoint)


# ------------------------------------------------------------------ framing
def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; ``None`` on clean EOF at a frame
    boundary (``n`` asked, zero read); ``protocol`` error mid-frame."""
    if n == 0:
        return b""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            if not buf:
                return None
            raise WireError(
                "protocol",
                f"connection closed mid-frame ({len(buf)}/{n} bytes).",
            )
        buf += chunk
    return bytes(buf)


def send_frame(
    sock: socket.socket, header: Dict[str, Any], payload: bytes = b""
) -> None:
    """Serialize and send one frame (header dict + binary payload).
    Scatter-gather (``sendmsg``) where the platform has it: composing
    ``head + header + payload`` into one bytes object re-copies the whole
    payload per frame — at config8's 32 MB batches that copy was a
    measurable slice of the wire gap (ISSUE 11)."""
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    head = _HEAD.pack(_MAGIC, len(hbytes), len(payload))
    if payload and hasattr(sock, "sendmsg"):
        _send_parts(sock, [head, hbytes, payload])
        return
    sock.sendall(head + hbytes + payload)


# segments per sendmsg call: Linux IOV_MAX is 1024 and sendmsg raises
# EMSGSIZE above it — chunk conservatively below the limit
_IOV_CHUNK = 1000


def _send_parts(sock: socket.socket, parts: List[Any]) -> None:
    # flat byte views only: short-write resumption below counts BYTES, and
    # a shaped (e.g. float32) memoryview's len()/slicing count elements
    parts = [
        p
        if isinstance(p, (bytes, bytearray))
        else memoryview(p).cast("B")
        for p in parts
    ]
    for start in range(0, len(parts), _IOV_CHUNK):
        chunk = parts[start : start + _IOV_CHUNK]
        sent = sock.sendmsg(chunk)
        for p in chunk:  # finish any short scatter write part by part
            if sent >= len(p):
                sent -= len(p)
                continue
            sock.sendall(p[sent:] if sent else p)
            sent = 0


def send_frame_parts(
    sock: socket.socket,
    header: Dict[str, Any],
    parts: List[Any],
    total: int,
) -> None:
    """:func:`send_frame` whose payload is a scatter-gather parts list
    (:func:`pack_tree_parts`): the payload bytes go from their owning
    buffers straight into the kernel — never assembled in user space."""
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    head = _HEAD.pack(_MAGIC, len(hbytes), total)
    if hasattr(sock, "sendmsg"):
        _send_parts(sock, [head, hbytes, *parts])
        return
    sock.sendall(b"".join([head, hbytes, *map(bytes, parts)]))


def _recv_prefix(
    sock: socket.socket,
) -> Optional[Tuple[Dict[str, Any], int]]:
    """Read and validate one frame's prefix (magic, sizes, JSON header);
    returns ``(header, payload_len)``, or ``None`` on clean EOF at a
    frame boundary. The ONE copy of the frame-prefix protocol shared by
    :func:`recv_frame` and :func:`recv_frame_into`."""
    head = _recv_exact(sock, _HEAD.size)
    if head is None:
        return None
    magic, hlen, plen = _HEAD.unpack(head)
    if magic != _MAGIC:
        raise WireError(
            "protocol",
            f"bad frame magic {magic!r} (expected {_MAGIC!r}) — not a "
            "torcheval-tpu eval-wire peer, or a protocol version skew.",
        )
    if hlen > _MAX_HEADER_BYTES or plen > _MAX_PAYLOAD_BYTES:
        raise WireError(
            "protocol", f"frame sizes out of range (hlen={hlen}, plen={plen})."
        )
    hbytes = _recv_exact(sock, hlen)
    if hbytes is None:
        raise WireError("protocol", "connection closed before header.")
    try:
        header = json.loads(hbytes)
    except json.JSONDecodeError as e:
        raise WireError("protocol", f"unparseable frame header: {e}") from None
    return header, plen


def recv_frame(
    sock: socket.socket,
) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Receive one frame; ``None`` on clean EOF. Raises
    :class:`WireError(reason="protocol")` on garbage — wrong magic,
    absurd lengths, unparseable header — so a client never retries
    against a peer that speaks something else."""
    prefix = _recv_prefix(sock)
    if prefix is None:
        return None
    header, plen = prefix
    payload = _recv_exact(sock, plen)
    if payload is None and plen:
        raise WireError("protocol", "connection closed before payload.")
    return header, payload or b""


def _recv_exact_into(sock: socket.socket, mv: memoryview) -> None:
    """Fill ``mv`` completely from the socket; ``protocol`` error on EOF
    mid-payload (the caller has already read this frame's header)."""
    want = len(mv)
    got = 0
    while got < want:
        n = sock.recv_into(mv[got:], min(want - got, 1 << 20))
        if not n:
            raise WireError(
                "protocol",
                f"connection closed mid-frame ({got}/{want} bytes).",
            )
        got += n


def recv_frame_into(
    sock: socket.socket, pool: Any
) -> Optional[Tuple[Dict[str, Any], Any, Any]]:
    """:func:`recv_frame`, but the payload lands in a pooled staging
    buffer instead of a fresh ``bytes`` object: returns ``(header,
    payload_view, stage)`` where ``stage`` is the
    :class:`~torcheval_tpu.serve.ingest.PooledBuffer` backing
    ``payload_view`` (``None`` for payloadless frames — then
    ``payload_view`` is ``b""``). The caller owns releasing the stage.
    The pooled fill is the timeline's ``serve.ingest.stage`` bar: the
    window in which this frame's bytes were landing in host memory."""
    prefix = _recv_prefix(sock)
    if prefix is None:
        return None
    header, plen = prefix
    if not plen:
        return header, b"", None
    t0 = time.perf_counter()
    stage = pool.acquire(plen)
    view = stage.view(plen)
    try:
        _recv_exact_into(sock, view)
    except BaseException:
        stage.release()
        raise
    if _obs._enabled:
        _trace.complete(
            "serve.ingest.stage",
            t0,
            time.perf_counter() - t0,
            kind="serve",
            bytes=plen,
        )
    return header, view, stage


# -------------------------------------------------------------- tree coding
def _encode_leaf(
    arr: np.ndarray, arrays: Dict[str, np.ndarray], codec: str
) -> Optional[Dict[str, Any]]:
    """Try the negotiated codec on one array leaf; register the encoded
    member(s) into ``arrays`` and return the self-describing spec node,
    or ``None`` when the leaf should ship raw (no win / wrong dtype /
    non-finite floats — the per-leaf raw fallback)."""
    if arr.dtype.kind in "iu":
        parts = _quant.delta_int_parts(arr)
        if parts is None:
            return None
        offset, data = parts
        key = f"a{len(arrays)}"
        arrays[key] = data
        return {
            "t": "darr",
            "i": key,
            "d": arr.dtype.str,
            "sh": list(arr.shape),
            "o": offset,
        }
    if codec == "qblk" and arr.dtype == np.float32:
        parts = _quant.q8_parts(arr)
        if parts is None:
            return None
        scales, q = parts
        key = f"a{len(arrays)}"
        skey = f"a{len(arrays) + 1}"
        arrays[key] = q
        arrays[skey] = scales
        return {"t": "qarr", "i": key, "s": skey, "sh": list(arr.shape)}
    return None


def _tree_encoder(arrays: Dict[str, np.ndarray], codec: str = "raw"):
    """The shared spec encoder behind :func:`pack_tree` and
    :func:`pack_tree_parts`: array leaves register into ``arrays``,
    re-encoded per the negotiated ``codec`` where that shrinks them."""

    def enc(x: Any) -> Any:
        if x is None or isinstance(x, (bool, int, float, str)):
            return {"t": "py", "v": x}
        if isinstance(x, dict):
            return {
                "t": "dict",
                "k": [enc(k) for k in x.keys()],
                "v": [enc(v) for v in x.values()],
            }
        if isinstance(x, (list, tuple)):
            return {
                "t": "list" if isinstance(x, list) else "tuple",
                "v": [enc(v) for v in x],
            }
        try:
            arr = np.asarray(x)
        except Exception:
            arr = None
        if arr is None or arr.dtype == object:
            # np.asarray swallows almost anything into an object array;
            # an object leaf would need pickling, which the wire refuses
            raise WireError(
                "protocol",
                f"cannot marshal {type(x).__name__} over the eval wire "
                "(dicts, lists, scalars and numeric array-likes only).",
            )
        if codec != "raw":
            node = _encode_leaf(arr, arrays, codec)
            if node is not None:
                return node
        key = f"a{len(arrays)}"
        arrays[key] = arr
        return {"t": "arr", "i": key}

    return enc


def pack_tree(obj: Any, codec: str = "raw") -> Tuple[Any, bytes]:
    """Encode a result/args tree (dicts, lists/tuples, scalars, arrays)
    into a JSON-safe spec plus ONE npz payload holding every array leaf.
    Anything with ``__array__`` (numpy, jax arrays, torch tensors)
    becomes an array leaf; exact dtype/shape survive the round trip.
    ``codec`` engages the negotiated leaf re-encoders (:data:`WIRE_CODECS`
    block comment) — only send it after the peer advertised support."""
    arrays: Dict[str, np.ndarray] = {}
    spec = _tree_encoder(arrays, codec)(obj)
    if not arrays:
        return spec, b""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return spec, buf.getvalue()


# zip structure constants for the scatter-gather packer
_ZIP_LOCAL = struct.Struct("<4s5H3I2H")
_ZIP_CENTRAL = struct.Struct("<4s6H3I5H2I")
_ZIP_EOCD = struct.Struct("<4s4H2IH")


def pack_tree_parts(
    obj: Any, codec: str = "raw"
) -> Tuple[Any, List[Any], int]:
    """:func:`pack_tree` for the ingest hot path: returns ``(spec, parts,
    total_len)`` where ``parts`` is a scatter-gather list whose array-data
    members are MEMORYVIEWS of the caller's own buffers — the payload is
    never assembled, ``send_frame`` hands the parts straight to
    ``sendmsg``. The archive is a STORED npz whose members' data offsets
    are 64-byte aligned (so the receiving :func:`unpack_tree` decodes
    zero-copy views), with one deliberate deviation: **member CRC32
    fields are zero**. Computing real CRCs costs one full pass over the
    payload per frame — the exact per-byte work this path exists to
    remove — and the repo's own decoder (``utils/npz.py``) never reads
    them. Foreign ``np.load`` consumers must use :func:`pack_tree`
    (checkpoints do: ``resilience.save`` keeps real npz + sha256).

    The caller must keep the encoded arrays alive until the send
    completes (the parts alias their buffers). ``codec`` as in
    :func:`pack_tree` (codec-encoded members are freshly-allocated
    narrow arrays, kept alive by the returned parts list itself)."""
    arrays: Dict[str, np.ndarray] = {}
    spec = _tree_encoder(arrays, codec)(obj)
    if not arrays:
        return spec, [], 0
    parts: List[Any] = []
    central = []
    offset = 0
    import zlib

    for key, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        name = f"{key}.npy".encode()
        dtype_descr = np.lib.format.dtype_to_descr(arr.dtype)
        header = (
            "{'descr': %r, 'fortran_order': False, 'shape': %r, }"
            % (dtype_descr, arr.shape)
        ).encode("latin1")
        # absolute 64-byte data alignment: pad the npy header (spaces
        # before the terminating newline, per the npy spec) so
        # data_start = offset + 30 + len(name) + 10 + hlen is 0 mod 64
        base_hlen = len(header) + 1
        data_start = offset + 30 + len(name) + 10 + base_hlen
        hlen = base_hlen + (-data_start) % 64
        npy_head = (
            b"\x93NUMPY\x01\x00"
            + struct.pack("<H", hlen)
            + header
            + b" " * (hlen - base_hlen)
            + b"\n"
        )
        size = len(npy_head) + arr.nbytes
        crc = 0
        if not isinstance(dtype_descr, str):
            # structured dtypes take the receiver's CHECKED copy fallback
            # (zipfile verifies member CRCs at EOF there), so they alone
            # pay the real checksum; plain-descr members ride the
            # CRC-blind zero-copy path (module doc above)
            crc = zlib.crc32(
                arr.data.cast("B"), zlib.crc32(npy_head)
            )
        local = _ZIP_LOCAL.pack(
            b"PK\x03\x04", 20, 0, 0, 0, 0, crc, size, size, len(name), 0
        )
        parts.append(local + name + npy_head)
        if arr.nbytes:
            # flat byte view: scatter-send bookkeeping counts bytes
            parts.append(arr.data.cast("B"))
        central.append((name, offset, size, crc))
        offset += 30 + len(name) + size
    cd_start = offset
    cd = bytearray()
    for name, off, size, crc in central:
        cd += _ZIP_CENTRAL.pack(
            b"PK\x01\x02", 20, 20, 0, 0, 0, 0, crc, size, size,
            len(name), 0, 0, 0, 0, 0, off,
        )
        cd += name
    cd += _ZIP_EOCD.pack(
        b"PK\x05\x06", 0, 0, len(central), len(central), len(cd), cd_start, 0
    )
    parts.append(bytes(cd))
    return spec, parts, cd_start + len(cd)


def unpack_tree(spec: Any, payload: Any) -> Any:
    """Inverse of :func:`pack_tree`. ``payload`` may be ``bytes`` or any
    buffer (a pooled staging view): aligned uncompressed leaves decode as
    zero-copy ``np.frombuffer`` views over the payload itself — no
    per-leaf heap allocation on the steady path — with a per-leaf copy
    fallback for compressed/misaligned/structured members
    (``utils/npz.py``; object arrays still reject exactly like
    ``allow_pickle=False``). The views pin the payload buffer (via
    ``ndarray.base``) for as long as any leaf lives, and are READ-ONLY
    when the payload is (a ``bytes`` frame) — callers that mutate a
    decoded result in place must copy it first (``np.load`` used to hand
    back fresh writable arrays here).

    Codec-encoded leaves (``darr``/``qarr`` nodes from a negotiated
    wire codec) are self-describing — the spec carries the decode
    recipe, so no codec argument is needed here. Their decode
    necessarily allocates (a cumsum / a dequantization), but the
    *encoded* members still stage zero-copy through the pool and the
    decoded array keeps the original (shape, dtype) signature, so the
    daemon's one-H2D-per-signature-group coalescing is unaffected."""
    arrays: Dict[str, np.ndarray] = {}
    if len(payload):
        try:
            arrays = npz_views(payload)
        except NPZ_FORMAT_ERRORS as e:
            raise WireError(
                "protocol", f"undecodable array payload: {e}"
            ) from None

    def dec(s: Any) -> Any:
        try:
            t = s["t"]
            if t == "py":
                return s["v"]
            if t == "dict":
                return {
                    dec(k): dec(v) for k, v in zip(s["k"], s["v"])
                }
            if t == "list":
                return [dec(v) for v in s["v"]]
            if t == "tuple":
                return tuple(dec(v) for v in s["v"])
            if t == "arr":
                return arrays[s["i"]]
            if t == "darr":
                return _quant.delta_int_from_parts(
                    arrays[s["i"]],
                    int(s["o"]),
                    np.dtype(s["d"]),
                    tuple(s["sh"]),
                )
            if t == "qarr":
                return _quant.q8_from_parts(
                    arrays[s["s"]], arrays[s["i"]], tuple(s["sh"])
                )
        except (KeyError, TypeError, IndexError, ValueError):
            # ValueError covers codec-node decode failures (a spec shape
            # that disagrees with the member's element count, a bad dtype
            # string): same malformed-frame classification as the rest
            pass
        raise WireError("protocol", f"malformed tree spec node: {s!r}.")

    return dec(spec)


# ------------------------------------------------------------------- errors
def _bare_message(exc: BaseException) -> str:
    """Strip the ``[reason]`` prefix ``ServeError.__init__`` composes, so
    a decode does not stack a second one."""
    msg = str(exc)
    reason = getattr(exc, "reason", None)
    prefix = f"[{reason}] "
    return msg[len(prefix):] if reason and msg.startswith(prefix) else msg


def encode_error(exc: BaseException) -> Dict[str, Any]:
    """Structured wire form of a serve-side failure: class name, reason,
    retryable flag, and the per-class extras (tenant/checkpoint)."""
    out: Dict[str, Any] = {
        "type": type(exc).__name__,
        "reason": getattr(exc, "reason", "internal"),
        "message": _bare_message(exc),
        "retryable": bool(getattr(exc, "retryable", False)),
    }
    for field in ("tenant", "checkpoint", "endpoint"):
        value = getattr(exc, field, None)
        if value is not None:
            out[field] = value
    return out


def decode_error(err: Dict[str, Any]) -> BaseException:
    """Reconstruct the exception :func:`encode_error` marshalled: the
    matching serve class when the type is known (so an except-clause
    written against local daemon calls works unchanged against the
    wire), a generic :class:`ServeError` otherwise. ``retryable`` is
    copied from the wire — the shared classification crosses intact."""
    from torcheval_tpu.resilience.snapshot import CheckpointError
    from torcheval_tpu.serve import errors as _errs

    name = err.get("type", "ServeError")
    reason = err.get("reason", "internal")
    message = err.get("message", "(no message)")
    tenant = err.get("tenant", "?")
    exc: BaseException
    if name == "BackpressureError":
        exc = _errs.BackpressureError(reason, message, tenant=tenant)
    elif name == "TenantQuarantinedError":
        exc = _errs.TenantQuarantinedError(reason, message, tenant=tenant)
    elif name == "TenantEvictedError":
        exc = _errs.TenantEvictedError(
            reason, message, tenant=tenant, checkpoint=err.get("checkpoint")
        )
    elif name == "TenantError":
        exc = _errs.TenantError(reason, message, tenant=tenant)
    elif name == "AdmissionError":
        exc = _errs.AdmissionError(reason, message)
    elif name == "WireError":
        exc = _errs.WireError(reason, message, endpoint=err.get("endpoint"))
    elif name == "CheckpointError":
        exc = CheckpointError(reason, message)
    elif name == "ValueError":
        exc = ValueError(message)
    else:
        exc = _errs.ServeError(reason, message)
    if hasattr(exc, "retryable") or "retryable" in err:
        exc.retryable = bool(err.get("retryable", False))
    return exc


# ------------------------------------------------------------- metric specs
def build_metrics(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Instantiate ``{name: Metric}`` from a wire metric spec
    ``{name: [class_name, kwargs]}`` — class names resolve against the
    public ``torcheval_tpu.metrics`` namespace only (no dotted paths, no
    pickles: a metric spec can never execute caller-chosen code). An
    unknown class or bad constructor args reject as
    ``AdmissionError("bad_metrics")``."""
    from torcheval_tpu import metrics as _metrics_ns
    from torcheval_tpu.metrics.metric import Metric

    if not isinstance(spec, dict) or not spec:
        raise AdmissionError(
            "bad_metrics", f"metric spec must be a non-empty dict, got {spec!r}."
        )
    out: Dict[str, Any] = {}
    for name, entry in spec.items():
        try:
            cls_name, kwargs = entry[0], (entry[1] if len(entry) > 1 else {})
        except (TypeError, IndexError, KeyError):
            raise AdmissionError(
                "bad_metrics",
                f"metric spec entry {name!r} must be [class_name, kwargs], "
                f"got {entry!r}.",
            ) from None
        cls = getattr(_metrics_ns, str(cls_name), None)
        if not (isinstance(cls, type) and issubclass(cls, Metric)):
            raise AdmissionError(
                "bad_metrics",
                f"metric spec entry {name!r} names {cls_name!r}, which is "
                "not a torcheval_tpu.metrics Metric class.",
            )
        try:
            out[name] = cls(**dict(kwargs or {}))
        except (TypeError, ValueError) as e:
            raise AdmissionError(
                "bad_metrics",
                f"constructing {cls_name}({kwargs!r}) for {name!r} failed: {e}",
            ) from e
    return out


# -------------------------------------------------------------- obs push
class _ObsPublisher:
    """One obs-push subscription: a thread that owns a handed-over
    connection and ships ``obs_push`` frames on a timer (see module doc).

    Timer discipline: fixed-rate scheduling against ``monotonic`` — a
    push that takes longer than ``interval_s`` (slow subscriber, giant
    delta) does not accumulate debt; the skipped ticks are counted into
    ``obs.stream.dropped`` (no telemetry is lost — the next delta folds
    everything since the cursor — but the *cadence* contract was missed
    and the subscriber deserves to know). The send carries a timeout: a
    peer that stops reading long enough to fill its socket buffer AND
    outlast the timeout is dropped entirely (a partial frame write is
    unrecoverable framing-wise), which bounds daemon-side cost at one
    in-flight frame per subscriber."""

    def __init__(
        self,
        server: "EvalServer",
        conn: socket.socket,
        interval_s: float,
    ) -> None:
        self._server = server
        self._conn = conn
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._send_lock = threading.Lock()
        self._cursor = None
        self._push_seq = 0
        self._thread = threading.Thread(
            target=self._run,
            name="torcheval-tpu-obs-publisher",
            daemon=True,
        )

    def start(self) -> None:
        # a wedged subscriber must not block a drain's final flush
        # indefinitely: bound every frame write
        try:
            self._conn.settimeout(max(5.0, 5.0 * self._interval_s))
        except OSError:
            pass
        daemon = self._server._daemon
        add_hook = getattr(daemon, "_add_flush_hook", None)
        if add_hook is not None:
            add_hook(self.flush)
        self._thread.start()

    def _run(self) -> None:
        interval = self._interval_s
        next_t = time.monotonic() + interval
        while not self._stop.is_set():
            timeout = next_t - time.monotonic()
            if timeout > 0 and self._stop.wait(timeout):
                break
            now = time.monotonic()
            missed = -1
            while next_t <= now:
                next_t += interval
                missed += 1
            if missed > 0 and _obs._enabled:
                _obs.counter("obs.stream.dropped", float(missed))
            try:
                from torcheval_tpu.obs import slo as _slo

                _slo.evaluate_slos()
            except Exception:  # noqa: BLE001 - a bad SLO can't kill pushes
                _logger.exception("obs-push: SLO evaluation raised")
            if not self._push():
                break
        self._retire()

    def _push(self) -> bool:
        """Ship one delta; False when the subscriber is gone/wedged."""
        from torcheval_tpu.obs import stream as _stream

        with self._send_lock:
            if self._stop.is_set():
                return False
            delta, cursor = _stream.collect(self._cursor)
            try:
                report = self._server._daemon.load_report()
            except Exception:  # noqa: BLE001 - report trouble != channel
                report = None
            self._push_seq += 1
            header = {
                "op": "obs_push",
                "push_seq": self._push_seq,
                "endpoint": self._server.endpoint,
                "delta": delta,
                "load_report": report,
            }
            try:
                send_frame(self._conn, header)
            except (OSError, ValueError):
                # socket.timeout is an OSError: a subscriber that cannot
                # take one frame within the bounded window is dropped and
                # the drop counted — never buffered against
                if _obs._enabled:
                    _obs.counter("obs.stream.dropped")
                return False
            # only advance the cursor on a successful write: a failed
            # push's changes stay pending (they would fold into the next
            # delta if the subscriber were still there)
            self._cursor = cursor
            if _obs._enabled:
                _obs.counter("obs.stream.pushes")
        return True

    def flush(self) -> None:
        """Synchronous final push (daemon drain()/stop() hook, and
        server.close()): the caller's thread ships the delta so the data
        is on the wire before the socket is severed."""
        if not self._stop.is_set():
            self._push()

    def stop(self) -> None:
        self._stop.set()

    def _retire(self) -> None:
        """Publisher exit path: deregister everywhere and close the
        socket (it was removed from request-response service at
        handover; nothing else will)."""
        daemon = self._server._daemon
        remove_hook = getattr(daemon, "_remove_flush_hook", None)
        if remove_hook is not None:
            remove_hook(self.flush)
        with self._server._lock:
            self._server._conns.discard(self._conn)
            try:
                self._server._publishers.remove(self)
            except ValueError:
                pass
        try:
            self._conn.close()
        except OSError:
            pass


# ------------------------------------------------------------------- server
class EvalServer:
    """TCP front end for one :class:`EvalDaemon`.

    Binds on construction (``port=0`` = OS-assigned, read it back from
    ``.address``) and serves immediately: an accept-loop thread plus one
    handler thread per connection — connection counts at eval-service
    scale are small (routers and producer fleets multiplex many tenants
    per connection), and a blocked tenant op never stalls another
    connection. All device work still happens on the daemon's single
    worker thread; handler threads only enqueue and wait on promises,
    exactly like local producer threads.

    Structured failures cross the wire via :func:`encode_error`; an
    unexpected handler exception is contained per-request (``ok=False``
    with reason ``"internal"``), never tearing the server down.
    """

    def __init__(
        self,
        daemon: Any,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        backlog: int = 32,
        codecs: Tuple[str, ...] = WIRE_CODECS,
        pipeline_depth: int = 32,
    ) -> None:
        from torcheval_tpu.serve.ingest import HostBufferPool

        self._daemon = daemon
        # payload codecs this server ACCEPTS (capability exchange at
        # attach; ``codecs=()`` models a raw-only peer — used by the
        # mixed-version interop tests, and a safe rollback knob)
        self._codecs = tuple(codecs)
        # max in-flight submit frames this server grants per pipelined
        # connection (ISSUE 18). The grant at attach is
        # min(client ask, this); ``pipeline_depth < 2`` never grants and
        # rejects ``pipeline_open`` as an unknown op — exactly how an
        # old server answers, so it doubles as the mixed-version rollback
        # knob (clients silently stay lock-step)
        if not isinstance(pipeline_depth, int) or pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be an int >= 0, got {pipeline_depth!r}."
            )
        self._pipeline_depth = pipeline_depth
        # shared staging pool: frame payloads land here and decode as
        # zero-copy views; slots recycle under the ingest aliasing
        # contract (serve/ingest.py)
        self._pool = HostBufferPool()
        self._sock = socket.create_server((host, port), backlog=backlog)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._handles: Dict[str, Any] = {}
        self._attach_nonces: Dict[str, Any] = {}
        # attach-time spec + knobs per tenant, served back by the
        # ``list_tenants`` op (ISSUE 20): a recovering router adopts an
        # orphan — a tenant live here but absent from its journal — only
        # if it can reconstruct the tenant's routing entry, and the spec
        # is not recoverable from the daemon (metrics are already built
        # objects there)
        self._tenant_meta: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._conns: set = set()
        self._publishers: list = []
        self._running = True
        # chaos host_partition: once tripped the server stops ACKing —
        # requests are read and dropped, modelling a half-dead host whose
        # TCP stack answers but whose service never does
        self._partitioned = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name="torcheval-tpu-eval-server-accept",
            daemon=True,
        )
        self._accept_thread.start()
        # same-process shared-memory transport (module comment at
        # _LOCAL_SERVERS): visible to clients only once fully constructed
        with _LOCAL_SERVERS_LOCK:
            _LOCAL_SERVERS[self.endpoint] = self

    @property
    def endpoint(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    def close(self) -> None:
        """Stop accepting AND sever live connections — a closed server is
        fully gone from the network's point of view (clients see dead
        sockets, not a listener that answers on old connections). Obs
        subscribers get a best-effort final push first."""
        self._running = False
        with _LOCAL_SERVERS_LOCK:
            if _LOCAL_SERVERS.get(self.endpoint) is self:
                del _LOCAL_SERVERS[self.endpoint]
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            publishers = list(self._publishers)
        for pub in publishers:
            try:
                pub.flush()
            except Exception:  # noqa: BLE001 - close must proceed
                pass
            pub.stop()
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def __enter__(self) -> "EvalServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ transport
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_connection,
                args=(conn,),
                name="torcheval-tpu-eval-server-conn",
                daemon=True,
            ).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        with self._lock:
            self._conns.add(conn)
        handed_over = False
        try:
            while self._running:
                try:
                    frame = recv_frame_into(conn, self._pool)
                except WireError as e:
                    _logger.warning("eval-wire: dropping connection: %s", e)
                    return
                except OSError:
                    # peer reset/closed the socket underneath the read (a
                    # failed health probe tearing down mid-accept): the
                    # connection is simply gone, same as a clean EOF
                    return
                if frame is None:
                    return
                header, payload, stage = frame
                if self._partitioned:
                    if stage is not None:
                        stage.release()
                    continue  # read and never answer (see class doc)
                response = self._dispatch(header, payload, stage)
                if response is None:
                    continue  # partition tripped ON this request
                pub = None
                if response[0].get("ok") and response[0].get("subscribed"):
                    # register the publisher BEFORE acking: the client
                    # treats the ack as "subscribed", so a close() racing
                    # this window must already see the publisher or the
                    # final-flush-on-close guarantee silently lapses
                    pub = _ObsPublisher(
                        self,
                        conn,
                        float(response[0]["interval_s"]),
                    )
                    with self._lock:
                        if not self._running:
                            return  # closing: never ack, just drop
                        self._publishers.append(pub)
                try:
                    send_frame(conn, *response)
                except OSError:
                    if pub is not None:
                        with self._lock:
                            try:
                                self._publishers.remove(pub)
                            except ValueError:
                                pass
                    return
                if pub is not None:
                    # ack sent: the connection now belongs to the
                    # publisher thread (it stays in _conns so close()
                    # severs it; the publisher discards + closes it when
                    # it retires)
                    handed_over = True
                    pub.start()
                    return
                if response[0].get("ok") and response[0].get("pipelined"):
                    # ack sent: the connection switches to deferred-ack
                    # service (ISSUE 18) — this thread keeps reading
                    # frames while a writer thread acks them as they
                    # commit. Returns when the peer goes away; the
                    # finally below closes the socket as usual.
                    self._serve_pipelined(conn, int(response[0]["depth"]))
                    return
        finally:
            if not handed_over:
                with self._lock:
                    self._conns.discard(conn)
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve_pipelined(self, conn: socket.socket, depth: int) -> None:
        """Deferred-ack service for one connection (ISSUE 18): this
        thread keeps READING frames while a writer thread dispatches
        them and sends acks back as batches commit — frame-receive and
        ack-send are decoupled, so up to ``depth`` frames ride the
        connection at once. The queue bound IS the server-side window:
        a slow dispatcher fills it, the reader stops draining the
        socket, and TCP backpressure holds the client's window — bounded
        memory per connection with no extra protocol machinery. Each ack
        echoes the frame's ``tenant`` and ``seq``/``seqs`` so the client
        matches order-independently; chaos ack actions (ack_delay /
        ack_reorder) inject at the ack write, the exact surface a real
        slow or reordered ack presents."""
        import queue as _queue

        frames: _queue.Queue = _queue.Queue(maxsize=max(1, depth))
        dead = threading.Event()

        def _ack_writer() -> None:
            held: Optional[Tuple[Dict[str, Any], bytes]] = None
            while True:
                item = frames.get()
                if item is None:
                    break
                header, payload, stage = item
                if dead.is_set() or self._partitioned:
                    if stage is not None:
                        stage.release()
                    continue
                # pipelined admission is gapless (EvalDaemon._submit):
                # with several frames of one tenant in flight, a seq
                # admitted past a shed hole would ratchet the dedup
                # watermark over it — tag every frame so the daemon
                # refuses out-of-order admission instead
                header = dict(header)
                header["gapless"] = True
                response = self._dispatch(header, payload, stage)
                if response is None:
                    continue  # partition tripped ON this request
                ack = dict(response[0])
                for key in ("tenant", "seq", "seqs"):
                    if key in header:
                        ack[key] = header[key]
                directive = None
                if _chaos.ack_armed():
                    directive = _chaos.on_host_ack(
                        str(header.get("op", "?")), header.get("tenant")
                    )
                if directive == "ack_delay":
                    time.sleep(_chaos.ack_delay_s())
                try:
                    if directive == "ack_reorder" and held is None:
                        held = (ack, response[1])
                        continue
                    self._write_ack(conn, ack, response[1])
                    if held is not None:
                        (ack, blob), held = held, None
                        self._write_ack(conn, ack, blob)
                except OSError:
                    # peer gone: stop answering, sever the socket so the
                    # reader wakes, and KEEP draining the queue (frames
                    # already read must still release their stages, and
                    # the reader must never block on a full window)
                    dead.set()
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
            if held is not None and not dead.is_set():
                try:
                    self._write_ack(conn, *held)
                except OSError:
                    pass

        writer = threading.Thread(
            target=_ack_writer,
            name="torcheval-tpu-eval-server-ack",
            daemon=True,
        )
        writer.start()
        try:
            while self._running and not dead.is_set():
                frame = recv_frame_into(conn, self._pool)
                if frame is None:
                    break
                frames.put(frame)
        except (WireError, OSError):
            pass
        finally:
            frames.put(None)
            writer.join(timeout=5.0)

    def _write_ack(
        self, conn: socket.socket, header: Dict[str, Any], payload: bytes
    ) -> None:
        if _obs._enabled:
            # every ack the deferred writer ships (vs the lock-step
            # request-response path, which never counts here)
            _obs.counter("serve.wire.acks_deferred")
        send_frame(conn, header, payload)

    # ------------------------------------------------------ local transport
    def local_request(
        self, header: Dict[str, Any], payload: Any
    ) -> Tuple[Dict[str, Any], bytes]:
        """Same-process request dispatch (ISSUE 18's shared-memory local
        transport): no socket, no frame codec. A ``bytes`` payload
        crosses AS the decode buffer — it is immutable, so the daemon's
        zero-copy npz views alias it safely for as long as they live
        (``stage=None``: nothing to recycle). A scatter-gather
        ``(parts, total)`` payload is assembled once into a staging-pool
        slot — the slot IS the buffer the daemon decodes, replacing the
        socket path's user→kernel→user round trip, and recycles under
        the same anchor-guarded aliasing contract as a socket-landed
        frame. Raises ``OSError`` when the server is closed or
        chaos-partitioned, so the client's transport-retry ladder treats
        a vanished local server exactly like a dead socket (and falls
        back to TCP once the endpoint deregisters)."""
        if not self._running:
            raise OSError("local transport: server is closed")
        total = (
            payload[1] if isinstance(payload, tuple) else len(payload)
        )
        stage: Any = None
        view: Any = b""
        if total:
            t0 = time.perf_counter()
            if not isinstance(payload, tuple):
                view = payload
            else:
                stage = self._pool.acquire(total)
                mv = stage.view(total)
                off = 0
                for part in payload[0]:
                    flat = (
                        part
                        if isinstance(part, (bytes, bytearray))
                        else memoryview(part).cast("B")
                    )
                    mv[off : off + len(flat)] = flat
                    off += len(flat)
                view = mv
            if _obs._enabled:
                # bytes that skipped the socket write+read copy pair
                _obs.counter(
                    "serve.ingest.local_copies_avoided_bytes", float(total)
                )
                _trace.complete(
                    "serve.ingest.stage",
                    t0,
                    time.perf_counter() - t0,
                    kind="serve",
                    bytes=total,
                )
        response = self._dispatch(header, view, stage)
        if response is None:
            raise OSError("local transport: host partitioned")
        return response

    # ------------------------------------------------------------- dispatch
    def _dispatch(
        self, header: Dict[str, Any], payload: Any, stage: Any = None
    ) -> Optional[Tuple[Dict[str, Any], bytes]]:
        op = str(header.get("op", "?"))
        tenant = header.get("tenant")
        if _obs._enabled:
            _obs.counter("serve.wire.requests", op=op)
            if payload is not None and len(payload):
                # received payload bytes per frame codec: with the raw
                # leg's bytes beside the encoded leg's, the wire's
                # compression ratio is readable straight off the registry
                _obs.counter(
                    "serve.wire.rx_bytes",
                    float(len(payload)),
                    codec=str(header.get("codec", "raw")),
                )
        if _chaos.host_armed():
            directive = _chaos.on_host_request(op, tenant)
            if directive == "partition":
                self._partitioned = True
                if stage is not None:
                    stage.release()
                return None
            # "ack_drop" processes below and dies before the ack
        else:
            directive = None
        # single-owner staging discipline: the box holds the stage until
        # the submit path TAKES it (just before handing it to the daemon,
        # which releases on every one of its own paths). The except arm
        # below frees only a stage still in the box — pre-handoff
        # failures (unpack errors, unknown tenants) — so a slot can never
        # be double-released across a pool recycle by two owners.
        stage_box = [stage]
        try:
            out_header, out_payload = self._handle(
                op, header, payload, stage_box
            )
            if stage_box[0] is not None:
                # a payload-bearing non-submit op: nothing took the stage
                stage_box[0].release()
                stage_box[0] = None
            response = ({"ok": True, **out_header}, out_payload)
        except BaseException as exc:  # noqa: BLE001 - containment wall
            if stage_box[0] is not None:
                stage_box[0].release()
            if not isinstance(exc, (ServeError, ValueError)) and not type(
                exc
            ).__name__.endswith("CheckpointError"):
                _logger.exception("eval-wire: %s request failed", op)
            response = ({"ok": False, "error": encode_error(exc)}, b"")
        if directive == "ack_drop":
            # process-then-die-before-ack: the host dies before ANY
            # answer leaves — including an error one; a request that
            # happened to reject must not quietly consume the one-shot
            # fault and let the drill pass without a fault
            _chaos.host_die("ack_drop")
        return response

    def _handle(
        self,
        op: str,
        header: Dict[str, Any],
        payload: Any,
        stage_box: Optional[list] = None,
    ) -> Tuple[Dict[str, Any], bytes]:
        if stage_box is None:
            stage_box = [None]
        if op == "health":
            return {"health": self._daemon.health()}, b""
        if op == "load_report":
            # the rebalancer's cheap pull (ISSUE 19): the schema-1 load
            # report alone, without the per-tenant health fold a full
            # probe pays. Old peers reject the op as protocol and the
            # client degrades to health()["load_report"].
            return {"load_report": self._daemon.load_report()}, b""
        if op == "list_tenants":
            # the recovering router's reconciliation pull (ISSUE 20):
            # authoritative per-tenant status + seq watermarks from the
            # daemon, joined with the attach-time spec/knobs this server
            # recorded so orphans are adoptable. Old peers reject the op
            # as protocol and the client degrades to health()["tenants"]
            # (no spec/knobs — orphans on old hosts stay unadopted).
            tenants = self._daemon.list_tenants()
            with self._lock:
                for tid, info in tenants.items():
                    meta = self._tenant_meta.get(tid)
                    if meta is not None:
                        info["spec"] = meta.get("spec")
                        info["knobs"] = meta.get("knobs")
            return {"tenants": tenants}, b""
        if op == "snapshot":
            from torcheval_tpu import obs

            spec, blob = pack_tree(
                {"snapshot": obs.snapshot(), "trace": obs.chrome_trace()}
            )
            return {"result": spec}, blob
        if op == "drain":
            drained = self._daemon.drain(timeout=header.get("timeout"))
            with self._lock:
                for tid in drained:
                    self._handles.pop(tid, None)
                    self._attach_nonces.pop(tid, None)
                    self._tenant_meta.pop(tid, None)
            return {"tenants": drained}, b""
        if op == "attach":
            return self._handle_attach(header)
        if op == "subscribe_obs":
            interval_s = header.get("interval_s", 1.0)
            try:
                interval_s = float(interval_s)
            except (TypeError, ValueError):
                interval_s = float("nan")
            if not (interval_s > 0.0) or interval_s != interval_s:
                raise WireError(
                    "bad_request",
                    f"subscribe_obs interval_s must be a positive number, "
                    f"got {header.get('interval_s')!r}.",
                )
            # the ack doubles as the handover signal: _serve_connection
            # sees "subscribed" in the ok response and hands the socket
            # to a publisher thread instead of reading another request
            return {"subscribed": True, "interval_s": interval_s}, b""
        if op == "pipeline_open":
            if self._pipeline_depth < 2:
                # answer exactly like a server that predates the op: the
                # client swallows the structural reject and stays
                # lock-step (mixed versions degrade, never break) — and
                # pipeline_depth=0 thereby models the old peer in tests
                raise WireError("protocol", f"unknown wire op {op!r}.")
            depth = header.get("depth")
            if not isinstance(depth, int) or isinstance(depth, bool) or (
                depth < 2
            ):
                raise WireError(
                    "bad_request",
                    f"pipeline_open depth must be an int >= 2, got "
                    f"{depth!r}.",
                )
            # the ack doubles as the handover signal, like subscribe_obs:
            # _serve_connection switches this connection to deferred-ack
            # service at the granted window
            return {
                "pipelined": True,
                "depth": min(depth, self._pipeline_depth),
            }, b""
        if op not in (
            "submit",
            "submit_many",
            "compute",
            "sync_compute",
            "flush",
            "detach",
        ):
            raise WireError("protocol", f"unknown wire op {op!r}.")
        # every remaining op targets one attached tenant
        handle = self._tenant_handle(str(header.get("tenant")))
        if op == "submit_many":
            return self._handle_submit_many(
                handle, header, payload, stage_box
            )
        if op == "submit":
            seq = int(header["seq"])
            args = unpack_tree(header["args"], payload)
            # the decoded args are zero-copy views over the pooled stage;
            # TAKE the stage out of the box — from here its lifetime is
            # the daemon's problem: it releases on every non-enqueue path
            # (even when submit raises) and, for admitted batches, after
            # the worker has placed the views on device
            stage, stage_box[0] = stage_box[0], None
            applied = handle.submit(
                *args, seq=seq, stage=stage, **self._admission(header)
            )
            return {
                "applied": applied,
                "acked_seq": handle._tenant.durable_seq,
            }, b""
        if op == "compute":
            result = handle.compute(timeout=header.get("timeout"))
            spec, blob = pack_tree(result)
            return {"result": spec}, blob
        if op == "sync_compute":
            result = handle.sync_compute(
                timeout_s=header.get("timeout_s"),
                on_failure=header.get("on_failure", "raise"),
                timeout=header.get("timeout"),
            )
            spec, blob = pack_tree(result)
            return {"result": spec}, blob
        if op == "flush":
            out = handle.flush(timeout=header.get("timeout"))
            return {"path": out["path"], "acked_seq": out["acked_seq"]}, b""
        if op == "detach":
            path = handle.detach(
                checkpoint=bool(header.get("checkpoint", False)),
                timeout=header.get("timeout"),
            )
            with self._lock:
                self._handles.pop(handle.tenant_id, None)
                self._attach_nonces.pop(handle.tenant_id, None)
                self._tenant_meta.pop(handle.tenant_id, None)
            return {"checkpoint": path}, b""
        raise AssertionError(op)  # pragma: no cover - gated above

    def _handle_submit_many(
        self,
        handle: Any,
        header: Dict[str, Any],
        payload: Any,
        stage_box: list,
    ) -> Tuple[Dict[str, Any], bytes]:
        """The client's coalesced submit: ONE frame carrying K seq'd
        batches (ISSUE 11 — the wire analog of the coalesced H2D group:
        frame overhead amortizes over the group instead of repeating per
        batch). Batches apply strictly in seq order; the single pooled
        stage backing every batch's views is reference-shared so it frees
        only when the LAST batch's device placement is done. On a
        mid-group failure the error surfaces with the whole group booked
        client-side — replay + seq dedup settle the split exactly-once."""
        from torcheval_tpu.serve.ingest import SharedStage

        seqs = header.get("seqs")
        batches = unpack_tree(header["args"], payload)
        if not isinstance(seqs, list) or len(seqs) != len(batches):
            raise WireError(
                "protocol",
                f"submit_many seqs/batches mismatch "
                f"({seqs!r} vs {len(batches)} batches).",
            )
        try:
            # validate BEFORE taking shares: once the SharedStage exists,
            # only handle.submit may consume a share per batch — a raise
            # from anywhere else would break the share accounting below
            seqs = [int(s) for s in seqs]
        except (TypeError, ValueError):
            raise WireError(
                "protocol", f"submit_many seqs must be ints, got {seqs!r}."
            ) from None
        # validations done: take the stage from the box — from here share
        # accounting (one per batch) owns the slot's lifetime
        stage, stage_box[0] = stage_box[0], None
        shared = (
            SharedStage(stage, len(batches))
            if stage is not None and batches
            else None
        )
        if shared is None and stage is not None:
            stage.release()  # a payload-bearing frame with zero batches
        admission = self._admission(header)
        applied = []
        try:
            for seq, args in zip(seqs, batches):
                applied.append(
                    handle.submit(*args, seq=seq, stage=shared, **admission)
                )
        except BaseException:
            if shared is not None:
                # the failing submit released its own share on its
                # no-enqueue path; the never-attempted tail's shares are
                # still ours
                for _ in range(len(batches) - len(applied) - 1):
                    shared.release()
            raise
        return {
            "applied": applied,
            "acked_seq": handle._tenant.durable_seq,
        }, b""

    @staticmethod
    def _admission(header: Dict[str, Any]) -> Dict[str, Any]:
        """Submit kwargs for the frame's transport mode. Pipelined frames
        (tagged ``gapless`` by ``_serve_pipelined``) admit gaplessly — a
        seq past a still-unadmitted hole is rejected retryably so the
        dedup watermark can never ratchet past a shed batch — and block
        briefly for queue space instead of shedding, because with a deep
        in-flight window a shed error ack forces the client into a full
        resend catch-up. Lock-step frames keep today's shed-immediately
        contract."""
        if not header.get("gapless"):
            return {}
        try:
            timeout = float(header.get("timeout") or 30.0)
        except (TypeError, ValueError):
            timeout = 30.0
        return {"gapless": True, "block": True, "timeout": timeout}

    def _negotiate_codec(self, header: Dict[str, Any]) -> Optional[str]:
        """Capability exchange: the first offered codec this server
        accepts, or ``None`` (= raw) when the client offered nothing or
        nothing overlaps. Old clients never offer; a ``codecs=()`` server
        never accepts — both degrade to raw with no protocol error."""
        offered = header.get("codecs")
        if not isinstance(offered, (list, tuple)):
            return None
        chosen = next((str(c) for c in offered if c in self._codecs), None)
        if _obs._enabled:
            _obs.counter("serve.wire.codec", codec=chosen or "raw")
        return chosen

    def _handle_attach(
        self, header: Dict[str, Any]
    ) -> Tuple[Dict[str, Any], bytes]:
        tenant_id = str(header.get("tenant"))
        nonce = header.get("nonce")
        codec = self._negotiate_codec(header)
        codec_fields = {"codec": codec} if codec else {}
        # pipeline negotiation rides the same capability exchange as the
        # codec (ISSUE 18): the client asks for a window, the server
        # grants min(ask, its own cap), and the granted depth comes back
        # in the attach ack. An old client never asks; an old server (or
        # pipeline_depth<2) never answers — either way the field is
        # absent and the wire stays lock-step with no protocol error.
        asked = header.get("pipeline")
        if (
            isinstance(asked, int)
            and not isinstance(asked, bool)
            and asked >= 2
            and self._pipeline_depth >= 2
        ):
            codec_fields["pipeline"] = min(asked, self._pipeline_depth)
        metrics = build_metrics(header.get("spec"))
        kwargs: Dict[str, Any] = {}
        for knob in (
            "nan_policy",
            "watchdog_timeout_s",
            "step_timeout_s",
            "queue_capacity",
            "resume",
            "window_chunks",
            "approx",
            "slices",
        ):
            if header.get(knob) is not None:
                kwargs[knob] = header[knob]
        try:
            handle = self._daemon.attach(tenant_id, metrics, **kwargs)
        except AdmissionError as e:
            if e.reason == "duplicate_tenant" and nonce is not None:
                # possibly a blind retry of OUR OWN attach whose ack was
                # lost (or whose original request is STILL mid-restore —
                # the daemon reserves the id before its checkpoint I/O):
                # attach is idempotent per nonce; wait for the original
                # to commit and re-ack its success. No submits can have
                # landed in between — the retrying client serializes
                # attach before them.
                deadline = time.monotonic() + 30.0
                while True:
                    with self._lock:
                        prior_nonce = self._attach_nonces.get(tenant_id)
                        prior_handle = self._handles.get(tenant_id)
                    if prior_handle is not None:
                        if prior_nonce == nonce:
                            return {
                                "last_seq": prior_handle._tenant.durable_seq,
                                **codec_fields,
                            }, b""
                        break  # a different caller's committed tenant
                    if (
                        not self._attach_pending(tenant_id)
                        or time.monotonic() >= deadline
                    ):
                        break  # no in-flight attach that could be ours
                    time.sleep(0.05)
            raise
        with self._lock:
            self._handles[tenant_id] = handle
            self._attach_nonces[tenant_id] = nonce
            self._tenant_meta[tenant_id] = {
                "spec": header.get("spec"),
                "knobs": dict(kwargs),
            }
        return {"last_seq": handle._tenant.durable_seq, **codec_fields}, b""

    def _attach_pending(self, tenant_id: str) -> bool:
        """True while the daemon holds ``tenant_id`` reserved for an
        in-flight admission (the restore-outside-the-lock window)."""
        daemon_lock = getattr(self._daemon, "_lock", None)
        attaching = getattr(self._daemon, "_attaching", None)
        if daemon_lock is None or attaching is None:
            return False
        with daemon_lock:
            return tenant_id in attaching

    def _tenant_handle(self, tenant_id: str):
        with self._lock:
            handle = self._handles.get(tenant_id)
        if handle is None:
            raise ServeError(
                "unknown_tenant",
                f"no tenant {tenant_id!r} attached over this wire; "
                "attach first.",
            )
        return handle

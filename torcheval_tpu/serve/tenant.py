"""Tenant state machine + the client-side handle.

A *tenant* is one eval stream served by the daemon: a
:class:`~torcheval_tpu.metrics.MetricCollection` it owns, a bounded
ingestion queue, and a lifecycle status. All device work happens on the
daemon's worker thread; the :class:`TenantHandle` a client holds only
enqueues work and waits on promises, so any number of producer threads can
feed one daemon — the many-producers / one-TPU-consumer topology
(Podracer, arXiv:2104.06272).

Lifecycle::

    ACTIVE --(poisoned batch / NaN policy / compute raise / step
              deadline)--> QUARANTINED     (structured error; slot held
                                            until detach; state suspect,
                                            never checkpointed)
    ACTIVE --(watchdog idle deadline / evict() / detach(checkpoint=True))
           --> EVICTED                     (state folded + checkpointed
                                            via resilience.save; slot
                                            freed; reattach resumes
                                            bit-identically)
    ACTIVE --(detach())--> DETACHED        (slot freed, state dropped)
"""

from __future__ import annotations

import enum
import threading
import time
from collections import deque
from typing import Any, Optional

from torcheval_tpu.serve.errors import ServeError

__all__ = ["TenantStatus", "TenantHandle"]


class TenantStatus(enum.Enum):
    ACTIVE = "active"
    QUARANTINED = "quarantined"
    EVICTED = "evicted"
    DETACHED = "detached"


class _Promise:
    """One worker-fulfilled result slot (compute/detach round trips)."""

    __slots__ = ("event", "value", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.value: Any = None
        self.error: Optional[BaseException] = None

    def resolve(self, value: Any) -> None:
        self.value = value
        self.event.set()

    def reject(self, error: BaseException) -> None:
        self.error = error
        self.event.set()

    def result(self, timeout: Optional[float]) -> Any:
        if not self.event.wait(timeout):
            raise ServeError(
                "result_timeout",
                f"daemon did not produce a result within {timeout}s "
                "(worker busy or stalled; see daemon.health()).",
            )
        if self.error is not None:
            raise self.error
        return self.value


class _Tenant:
    """Daemon-internal per-tenant record. Mutated only under the daemon
    lock (status, queue, stats) or on the worker thread (collection)."""

    __slots__ = (
        "id",
        "collection",
        "queue",
        "capacity",
        "status",
        "error",
        "nan_policy",
        "watchdog_timeout_s",
        "step_timeout_s",
        "last_activity",
        "ingested",
        "processed",
        "sheds",
        "seq",
        "last_seq",
        "applied_seq",
        "durable_seq",
        "dupes",
    )

    def __init__(
        self,
        tenant_id: str,
        collection: Any,
        *,
        capacity: int,
        nan_policy: str,
        watchdog_timeout_s: Optional[float],
        step_timeout_s: Optional[float],
        seq: int,
    ) -> None:
        self.id = tenant_id
        self.collection = collection
        self.queue: deque = deque()
        self.capacity = capacity
        self.status = TenantStatus.ACTIVE
        self.error: Optional[BaseException] = None
        self.nan_policy = nan_policy
        self.watchdog_timeout_s = watchdog_timeout_s
        self.step_timeout_s = step_timeout_s
        self.last_activity = time.monotonic()
        self.ingested = 0
        self.processed = 0
        self.sheds = 0
        self.seq = seq
        # wire-sequence bookkeeping (ISSUE 10): highest client sequence
        # number ADMITTED to the queue (the dedup watermark — a replayed
        # submit at or below it is acknowledged without re-applying),
        # highest APPLIED into the collection (worker thread only), and
        # highest covered by a published checkpoint (the durable
        # watermark an ack reports so clients can prune replay buffers).
        # All 0 for tenants never driven over the wire (seq=None submits
        # leave them untouched).
        self.last_seq = 0
        self.applied_seq = 0
        self.durable_seq = 0
        self.dupes = 0


class TenantHandle:
    """Client-side handle to one attached tenant.

    Thread-safe: every method takes the daemon lock for its bookkeeping
    and never touches the device — ``submit`` enqueues, ``compute`` /
    ``detach`` enqueue a promise and block on the worker's answer. After a
    quarantine or eviction, every method raises the tenant's structured
    terminal error (:class:`~torcheval_tpu.serve.TenantQuarantinedError` /
    :class:`~torcheval_tpu.serve.TenantEvictedError`), so a producer loop
    finds out on its next call, with the reason attached.
    """

    __slots__ = ("_daemon", "_tenant")

    def __init__(self, daemon: Any, tenant: _Tenant) -> None:
        self._daemon = daemon
        self._tenant = tenant

    # ------------------------------------------------------------- queries
    @property
    def tenant_id(self) -> str:
        return self._tenant.id

    @property
    def status(self) -> TenantStatus:
        return self._tenant.status

    @property
    def error(self) -> Optional[BaseException]:
        """The structured terminal error (quarantine/eviction), if any."""
        return self._tenant.error

    # ---------------------------------------------------------------- ops
    def submit(
        self,
        *args: Any,
        block: bool = False,
        timeout: Optional[float] = None,
        seq: Optional[int] = None,
        stage: Any = None,
        gapless: bool = False,
    ) -> bool:
        """Enqueue one update batch (the metric ``update`` positional
        args). Returns once queued; the device work happens on the daemon
        worker. On a full queue: ``block=False`` sheds with
        :class:`~torcheval_tpu.serve.BackpressureError` (reason
        ``"queue_full"``), ``block=True`` waits up to ``timeout`` seconds
        for space (then sheds). ``seq`` is the wire layer's per-tenant
        monotonic sequence number: a resubmit at or below the admitted
        watermark is acknowledged without re-applying (returns ``False``)
        — exactly-once into the metric state under at-least-once
        delivery. ``stage`` is the pooled staging buffer backing ``args``
        (the wire's zero-copy ingest path); ownership transfers to the
        daemon, which releases it on EVERY path — after the batch's
        device placement, or immediately when the batch is deduplicated,
        shed, or dropped with a quarantined tenant. Returns ``True`` when
        the batch was admitted. ``gapless`` (the pipelined wire path,
        ISSUE 18) additionally refuses a ``seq`` past a still-unadmitted
        hole with a retryable ``seq_gap`` reject — see
        ``EvalDaemon._submit``."""
        return self._daemon._submit(
            self._tenant, args, block=block, timeout=timeout, seq=seq,
            stage=stage, gapless=gapless,
        )

    def flush(self, *, timeout: Optional[float] = None) -> dict:
        """Fold and checkpoint this tenant's current state WITHOUT
        evicting it: ``{"path": ckpt_dir, "acked_seq": durable_watermark}``.
        The wire client calls this to advance the durable watermark when
        its bounded replay buffer fills; local callers get a midstream
        resume point for free. The tenant stays ACTIVE and continues
        bit-identically."""
        return self._daemon._request(self._tenant, "flush", timeout=timeout)

    def compute(self, *, timeout: Optional[float] = None) -> Any:
        """Drain this tenant's queued batches, close its eval window and
        return the metric results (the collection's ``compute()`` shape).
        Blocks up to ``timeout`` seconds for the worker's answer."""
        return self._daemon._request(self._tenant, "compute", timeout=timeout)

    def sync_compute(
        self,
        *,
        timeout_s: Optional[float] = None,
        on_failure: str = "raise",
        timeout: Optional[float] = None,
    ) -> Any:
        """Cross-process ``sync_and_compute_collection`` of this tenant's
        metrics, run on the worker thread under the PR 5 deadline contract
        (``timeout_s`` bounds the collective rounds; ``on_failure="local"``
        degrades to this rank's local results). The client blocks until the
        worker answers, which keeps multi-rank call order in lockstep —
        call it for the same tenants in the same order on every rank."""
        return self._daemon._request(
            self._tenant,
            "sync_compute",
            timeout=timeout,
            payload={"timeout_s": timeout_s, "on_failure": on_failure},
        )

    def detach(
        self, *, checkpoint: bool = False, timeout: Optional[float] = None
    ) -> Optional[str]:
        """Release this tenant's slot after the worker drains its queue.
        With ``checkpoint=True`` the state is folded and saved first
        (returns the checkpoint path — the graceful spelling of eviction);
        otherwise the state is dropped and ``None`` returns. Detaching an
        already-quarantined/evicted tenant just clears the slot."""
        return self._daemon._detach(
            self._tenant, checkpoint=checkpoint, timeout=timeout
        )

    def __repr__(self) -> str:
        t = self._tenant
        return (
            f"TenantHandle({t.id!r}, {t.status.value}, "
            f"queued={len(t.queue)})"
        )

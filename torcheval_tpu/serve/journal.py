"""``serve.journal``: the router's durable control-plane log (ISSUE 20).

The :class:`~torcheval_tpu.serve.router.EvalRouter` holds the fleet's
tenant directory — placements, split fan-out topology, host membership —
only in memory. This module makes that state survive a router crash
without putting an fsync on the data path's hot loop:

* **WAL** (``wal.log``): one CRC32-framed JSON line per control-plane
  mutation (place, remove, move, split, host add/remove), ``fsync``'d
  before :meth:`RouterJournal.append` returns. Control-plane ops are
  rare (human/rebalancer timescale), so the per-record fsync is free
  where it matters; submits never touch the journal — the reconciliation
  pass recovers seq watermarks from the hosts themselves.
* **Snapshot compaction** (``snapshot.json``): the full routing table,
  written temp-then-``os.replace`` so a crash mid-compaction leaves the
  previous snapshot intact. Every record carries a monotonically
  increasing ``seq`` and the snapshot stamps the highest seq it folded
  in (``last_seq``), so the crash window *between* publishing a snapshot
  and truncating the WAL replays exactly once: replay skips WAL records
  at or below the snapshot watermark.
* **Torn-tail tolerance**: a process killed mid-``write`` leaves a
  truncated or garbled final record. Replay verifies each line's CRC and
  stops at the first bad one — dropped and counted
  (``serve.router.journal_torn_tails``), never a crash. Everything
  before the tear is intact (records are appended and fsync'd strictly
  in order).

Obs counters: ``serve.router.journal_records`` (appends),
``serve.router.journal_compactions``, ``serve.router.journal_torn_tails``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from torcheval_tpu.obs import registry as _obs

_logger = logging.getLogger(__name__)

_WAL = "wal.log"
_SNAPSHOT = "snapshot.json"


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platform without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _frame(record: Dict[str, Any]) -> bytes:
    body = json.dumps(record, sort_keys=True, separators=(",", ":")).encode(
        "utf-8"
    )
    return b"%08x %s\n" % (zlib.crc32(body) & 0xFFFFFFFF, body)


def _parse_line(line: bytes) -> Optional[Dict[str, Any]]:
    """One framed record, or ``None`` for a torn/corrupt line."""
    if not line.endswith(b"\n"):
        return None  # truncated mid-write: the torn tail itself
    head, sep, body = line[:-1].partition(b" ")
    if not sep or len(head) != 8:
        return None
    try:
        want = int(head, 16)
    except ValueError:
        return None
    if zlib.crc32(body) & 0xFFFFFFFF != want:
        return None
    try:
        record = json.loads(body)
    except ValueError:
        return None  # CRC'd garbage cannot happen, but never crash here
    return record if isinstance(record, dict) else None


class RouterJournal:
    """Append-only fsync'd WAL + snapshot compaction for router state.

    ``snapshot_fn`` (optional) returns the caller's full state dict; when
    set, :meth:`append` auto-compacts after ``compact_every`` records so
    the WAL stays bounded without the router scheduling anything. The
    callback runs on the appending thread — the router passes a bound
    method and already holds its own re-entrant lock there.
    """

    def __init__(
        self,
        directory: str,
        *,
        snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        compact_every: int = 256,
    ) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._snapshot_fn = snapshot_fn
        self._compact_every = max(int(compact_every), 1)
        self._lock = threading.Lock()
        self._closed = False
        self._wal_path = os.path.join(self.directory, _WAL)
        snapshot, records, next_seq, good_bytes = self._load()
        self._seq = next_seq  # next record seq to assign
        self._since_compaction = len(records)
        self._wal = open(self._wal_path, "ab")
        if self._wal.tell() != good_bytes:
            # a torn tail was dropped at replay: cut the file back to the
            # last good record, or the next append would glue itself onto
            # the garbage and be dropped with it at the NEXT replay
            self._wal.truncate(good_bytes)
            self._wal.seek(good_bytes)
            os.fsync(self._wal.fileno())

    # ------------------------------------------------------------------ read
    def _load(
        self,
    ) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]], int, int]:
        """(snapshot state, live WAL records, next seq, good WAL bytes)
        from disk. ``good bytes`` is the offset of the first torn/corrupt
        record — the constructor truncates the WAL back to it."""
        snapshot: Optional[Dict[str, Any]] = None
        snap_seq = 0
        snap_path = os.path.join(self.directory, _SNAPSHOT)
        try:
            with open(snap_path, "rb") as f:
                loaded = json.loads(f.read().decode("utf-8"))
            if isinstance(loaded, dict) and isinstance(
                loaded.get("state"), dict
            ):
                snapshot = loaded["state"]
                snap_seq = int(loaded.get("last_seq", 0))
        except FileNotFoundError:
            pass
        except (OSError, ValueError, TypeError):
            # snapshots publish atomically, so a bad one is disk rot, not
            # a torn write; reconciliation against live hosts re-derives
            # what the snapshot held — degrade, never crash
            _logger.error(
                "router journal: unreadable snapshot %s; recovering from "
                "the WAL and live-host reconciliation only.",
                snap_path,
            )
            _obs.counter("serve.router.journal_torn_tails", reason="snapshot")
        records: List[Dict[str, Any]] = []
        last_seq = snap_seq
        good_bytes = 0
        try:
            with open(self._wal_path, "rb") as f:
                lines = f.readlines()
        except FileNotFoundError:
            lines = []
        for i, line in enumerate(lines):
            record = _parse_line(line)
            if record is None:
                # the torn tail: drop this record and (defensively)
                # anything after it — order is the journal's one
                # integrity guarantee, so nothing past a tear is trusted
                dropped = len(lines) - i
                _logger.warning(
                    "router journal: torn/corrupt record at line %d of "
                    "%s; dropped %d record(s) after the last good one.",
                    i + 1,
                    _WAL,
                    dropped,
                )
                _obs.counter("serve.router.journal_torn_tails", reason="wal")
                break
            good_bytes += len(line)
            seq = int(record.get("seq", 0))
            last_seq = max(last_seq, seq)
            if seq <= snap_seq:
                # folded into the snapshot already (crash between snapshot
                # publish and WAL truncation): skip, exactly-once replay
                continue
            records.append(record)
        self._last_loaded = (snapshot, records)
        return snapshot, records, last_seq + 1, good_bytes

    def replay(
        self,
    ) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
        """The durable history: (compacted state or None, ordered WAL
        records newer than it). Reflects disk at construction time —
        :class:`RouterJournal` is a single-writer log, so the constructor
        read is authoritative for the recovering process."""
        return self._last_loaded

    # ----------------------------------------------------------------- write
    def append(self, kind: str, **fields: Any) -> int:
        """Durably append one control-plane record; returns its seq.
        The record is on disk (fsync) when this returns — a router crash
        immediately after cannot lose it."""
        with self._lock:
            if self._closed:
                raise ValueError("RouterJournal is closed.")
            seq = self._seq
            self._seq += 1
            record = {"seq": seq, "kind": str(kind), **fields}
            self._wal.write(_frame(record))
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._since_compaction += 1
            _obs.counter("serve.router.journal_records", kind=str(kind))
            should_compact = (
                self._snapshot_fn is not None
                and self._since_compaction >= self._compact_every
            )
        if should_compact:
            self.compact(self._snapshot_fn())
        return seq

    def compact(self, state: Dict[str, Any]) -> None:
        """Publish ``state`` as the new snapshot (temp-then-replace) and
        truncate the WAL. Crash-safe at every point: before the replace
        the old snapshot + full WAL stand; between the replace and the
        truncation, replay skips WAL records the snapshot already folded
        in (seq watermark)."""
        with self._lock:
            if self._closed:
                raise ValueError("RouterJournal is closed.")
            last_seq = self._seq - 1
            snap_path = os.path.join(self.directory, _SNAPSHOT)
            tmp = snap_path + ".tmp"
            body = json.dumps(
                {"format_version": 1, "last_seq": last_seq, "state": state},
                sort_keys=True,
            ).encode("utf-8")
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, snap_path)
            _fsync_dir(self.directory)
            # now safe to drop the WAL: everything in it is <= last_seq
            self._wal.close()
            self._wal = open(self._wal_path, "wb")
            self._since_compaction = 0
            _obs.counter("serve.router.journal_compactions")

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._wal.flush()
                os.fsync(self._wal.fileno())
            except (OSError, ValueError):
                pass
            self._wal.close()

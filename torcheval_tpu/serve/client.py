"""`EvalClient`: the producer-side endpoint of the eval wire.

One client speaks to ONE host (an :class:`~torcheval_tpu.serve.EvalServer`
in front of an :class:`~torcheval_tpu.serve.EvalDaemon`); the cluster
router (``serve/router.py``) composes one client per endpoint. The client
owns every *unreliable-network* concern so callers see the same
structured-error surface a local :class:`TenantHandle` gives:

* **per-request deadlines** — every request runs under a socket timeout
  (``request_timeout_s`` default, overridable per call), validated at the
  boundary by the same ``_check_timeout_s`` every serve/sync deadline
  knob uses;
* **retry with exponential backoff + jitter** — transport failures and
  *retryable* structured errors (a shed, a capacity reject: the shared
  ``retryable`` classification from ``serve/errors.py``) retry up to
  ``max_attempts`` with the ``init_from_env`` backoff shape (×2 growth,
  cap, 0.5–1.5× jitter); non-retryable errors surface immediately;
* **a per-host circuit breaker** — ``breaker_threshold`` consecutive
  transport failures open the circuit and further calls fail fast with
  ``WireError("circuit_open")`` (no socket touched) until
  ``breaker_reset_s`` elapses and a half-open probe is allowed through;
* **bounded in-flight** — at most ``max_in_flight`` requests on the wire
  at once (a semaphore over the connection pool): client-side
  backpressure composes with the daemon's queue bounds instead of hiding
  them;
* **idempotent submits + a bounded replay buffer** — each submit carries
  a per-tenant monotonic ``seq`` and is held in a bounded replay buffer
  until an ack reports it *durable* (covered by a published checkpoint).
  A resend after an ambiguous failure is deduplicated server-side, so
  blind retries are safe; when the buffer fills, the client issues a
  ``flush`` (checkpoint-without-evicting) to advance the durable
  watermark and prune. The router migrates a dead host's tenants by
  restoring their checkpoints elsewhere and replaying exactly this
  buffer's un-durable tail;
* **deferred-ack pipelining** (ISSUE 18) — with ``pipeline_depth > 1``
  (and a server that granted it at attach), submits stream on a
  dedicated channel socket up to that many frames ahead of their acks,
  so producer throughput is bounded by bandwidth instead of round-trip
  latency. Exactly-once needs no new client invariants: every streamed
  frame is already booked in the replay buffer, acks ride back
  asynchronously carrying the same ``acked_seq`` watermark, and any
  failure (error ack, dead channel, timeout) flags the existing
  ``needs_resend`` catch-up — the lock-step replay path settles
  delivery. The server admits pipelined frames *gaplessly* (a seq past
  a shed hole is rejected retryably), so the dedup watermark can never
  ratchet over an unapplied batch. Old servers never grant, so mixed
  versions silently run lock-step — degrade, never break;
* **shared-memory local transport** (ISSUE 18) — when the server lives
  in this process, ``submit``/``submit_many`` payloads are handed to it
  directly: the staging-pool slot (or the immutable payload bytes) IS
  the buffer the daemon's zero-copy npz views decode from, skipping
  the socket write+read copy pair. Byte-identical semantics to TCP
  (same dispatch, same structured errors); TCP is the automatic
  fallback the moment the endpoint is not locally registered.
"""

from __future__ import annotations

import random
import socket
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.serve.errors import ServeError, WireError
from torcheval_tpu.serve.wire import (
    decode_error,
    local_server,
    pack_tree,
    pack_tree_parts,
    recv_frame,
    send_frame,
    send_frame_parts,
    unpack_tree,
)

__all__ = ["EvalClient", "ObsSubscription", "metric_spec"]

_UNSET = object()


def metric_spec(class_name: str, **kwargs: Any) -> List[Any]:
    """One wire metric-spec entry: ``metric_spec("MulticlassAccuracy",
    num_classes=10)``. Class names resolve server-side against
    ``torcheval_tpu.metrics`` only."""
    return [class_name, kwargs]


class _ClientTenant:
    """Client-side per-tenant wire state (sequence numbers + replay)."""

    __slots__ = (
        "lock",
        "next_seq",
        "durable_seq",
        "replay",
        "sendbuf",
        "migrated",
        "needs_resend",
        "codec",
    )

    def __init__(self, last_seq: int, codec: str = "raw") -> None:
        self.lock = threading.Lock()
        # the payload codec negotiated for this tenant at attach ("raw"
        # when the server accepted none): drives every submit/replay pack
        self.codec = codec
        self.next_seq = last_seq + 1
        self.durable_seq = last_seq
        self.replay: deque = deque()  # (seq, np-args tuple), seq ascending
        # booked-but-unsent tail under submit_buffer coalescing: every
        # entry here is ALSO in replay (booked at submit time), so a
        # crash/migration between booking and the coalesced send loses
        # nothing — the replay path delivers it
        self.sendbuf: list = []
        # set (under lock) by export_tenant: a concurrent submitter that
        # grabbed this state object before the export must NOT book a
        # batch into it — the buffer has already been carried elsewhere
        self.migrated = False
        # set when a booked submit escaped with a transport failure: the
        # next submit/flush must re-deliver the booked tail FIRST (dedup
        # absorbs any that actually landed) — otherwise a later batch
        # advances the daemon watermark past the hole and a flush prunes
        # the never-applied entry as "durable"
        self.needs_resend = False


class ObsSubscription:
    """One live obs stream from a host (``EvalClient.subscribe_obs``).

    ``mode`` is ``"push"`` when the server speaks the ISSUE 16 push
    channel (a dedicated socket outside the request pool carries
    ``obs_push`` frames on the server's timer) or ``"poll"`` when the
    peer rejected the op structurally — an OLD server — and the
    subscription degraded to calling ``health()`` on the same cadence
    (mixed versions degrade, never break). Either way ``on_push`` fires
    with one message dict per tick and :attr:`last` holds the newest;
    push messages carry ``delta`` + ``load_report``, poll messages carry
    ``load_report`` + the full ``health`` dict (no delta — polling has
    no cursor). ``stop()`` is idempotent and joins the reader thread."""

    def __init__(
        self,
        endpoint: str,
        interval_s: float,
        on_push: Optional[Any] = None,
    ) -> None:
        self.endpoint = endpoint
        self.interval_s = interval_s
        self.mode: Optional[str] = None
        self.last: Optional[Dict[str, Any]] = None
        self.last_at: Optional[float] = None
        self.received = 0
        self._on_push = on_push
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def alive(self) -> bool:
        """True while the reader/poller thread runs (a dead host ends a
        push subscription; a poll subscription keeps trying)."""
        return self._thread is not None and self._thread.is_alive()

    def _record(self, msg: Dict[str, Any]) -> None:
        self.last = msg
        self.last_at = time.monotonic()
        self.received += 1
        if self._on_push is not None:
            try:
                self._on_push(msg)
            except Exception:  # noqa: BLE001 - a bad callback can't kill
                pass  # the stream; next tick still delivers

    def stop(self) -> None:
        self._stop.set()
        sock = self._sock
        if sock is not None:
            # the push reader blocks in recv: severing the socket wakes it
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)


class _PipelinedChannel:
    """One deferred-ack submit stream to a host (ISSUE 18).

    A dedicated socket (outside the request pool) carries up to
    ``depth`` un-acked ``submit``/``submit_many`` frames; a reader
    thread parks each ack under the channel condition and holders of a
    TENANT's state lock fold their own parked acks in
    (:meth:`fold_locked`). The reader never takes a tenant lock, so the
    ack path and the submit path have no lock-order coupling — a
    submitter blocked on the window cannot deadlock the reader that
    would free it.

    Failure model: any socket error, EOF, or window-wait timeout kills
    the WHOLE channel (``_fail``) — every tenant with frames still in
    flight is marked dirty and folds into ``needs_resend`` on its next
    ``fold_locked``, after which the lock-step replay path settles
    delivery exactly-once (server-side gapless admission guarantees the
    dedup watermark never passed the hole). The owning client just
    opens a fresh channel on the next submit.
    """

    def __init__(
        self, sock: socket.socket, depth: int, endpoint: str
    ) -> None:
        self._sock = sock
        self.depth = depth
        self.endpoint = endpoint
        self._cv = threading.Condition()
        self._send_lock = threading.Lock()
        # (tenant_id, seq-tuple) -> True for every streamed, un-acked
        # frame; the dict size is the window occupancy
        self._inflight: Dict[Tuple[str, tuple], bool] = {}
        # tenant_id -> parked ack headers, folded by state.lock holders
        self._pending: Dict[str, List[Dict[str, Any]]] = {}
        self._dead: Optional[BaseException] = None
        # tenants that had frames in flight when the channel died: their
        # next fold flags needs_resend
        self._dirty: set = set()
        self._reader = threading.Thread(
            target=self._read_loop,
            name="torcheval-tpu-pipeline-acks",
            daemon=True,
        )
        self._reader.start()

    @property
    def alive(self) -> bool:
        with self._cv:
            return self._dead is None

    # ---------------------------------------------------------- reader side
    def _read_loop(self) -> None:
        while True:
            try:
                frame = recv_frame(self._sock)
            except (OSError, WireError) as e:
                self._fail(e)
                return
            if frame is None:
                self._fail(
                    WireError(
                        "transport",
                        f"{self.endpoint} closed the pipeline channel.",
                        endpoint=self.endpoint,
                    )
                )
                return
            header, _payload = frame
            tenant = str(header.get("tenant"))
            seqs = header.get("seqs")
            if seqs is None:
                seqs = [header.get("seq")]
            try:
                key = (tenant, tuple(int(s) for s in seqs))
            except (TypeError, ValueError):
                key = (tenant, ())
            with self._cv:
                self._inflight.pop(key, None)
                self._pending.setdefault(tenant, []).append(header)
                self._cv.notify_all()

    def _fail(self, exc: BaseException) -> None:
        with self._cv:
            self._fail_locked(exc)
        try:
            self._sock.close()
        except OSError:
            pass

    def _fail_locked(self, exc: BaseException) -> None:
        if self._dead is None:
            self._dead = exc
        for tenant, _seqs in self._inflight:
            self._dirty.add(tenant)
        self._inflight.clear()
        self._cv.notify_all()
        try:
            # wake the reader if it is parked in recv
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    # -------------------------------------------------------- tenant folding
    @staticmethod
    def _fold_acks(
        state: "_ClientTenant", acks: List[Dict[str, Any]], dirty: bool
    ) -> None:
        for header in acks:
            if header.get("ok"):
                state.durable_seq = max(
                    state.durable_seq, int(header.get("acked_seq", 0))
                )
            else:
                # a structured reject mid-pipeline: the frame's batches
                # (and, through gapless admission, everything streamed
                # after them) stay booked — lock-step replay settles it
                state.needs_resend = True
        if dirty:
            state.needs_resend = True
        while state.replay and state.replay[0][0] <= state.durable_seq:
            state.replay.popleft()

    def fold_locked(self, tenant_id: str, state: "_ClientTenant") -> None:
        """Fold this tenant's parked acks into its wire state (caller
        holds ``state.lock``). Never raises and never blocks on the
        socket: an error ack or a dead channel just flags
        ``needs_resend`` for the caller's catch-up path."""
        with self._cv:
            acks = self._pending.pop(tenant_id, [])
            dirty = tenant_id in self._dirty
            self._dirty.discard(tenant_id)
        self._fold_acks(state, acks, dirty)

    # ---------------------------------------------------------- submit side
    def send(
        self,
        tenant_id: str,
        state: "_ClientTenant",
        header: Dict[str, Any],
        payload: Any,
        timeout_s: Optional[float],
    ) -> None:
        """Stream one already-BOOKED frame, waiting (bounded by
        ``timeout_s``) for window space. Caller holds ``state.lock``.
        Raises ``WireError`` with ``request_sent=True`` on channel
        death/timeout — the caller marks ``needs_resend`` and
        ``batch_booked`` exactly like an ambiguous lock-step submit."""
        seqs = header.get("seqs")
        key = (
            tenant_id,
            tuple(seqs) if seqs is not None else (header["seq"],),
        )
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        with self._cv:
            while (
                self._dead is None and len(self._inflight) >= self.depth
            ):
                remaining = (
                    None
                    if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    # a window that never frees means the host stopped
                    # acking: poison the channel so every tenant's next
                    # fold goes through the resend path
                    self._fail_locked(
                        WireError(
                            "request_timeout",
                            f"pipeline window to {self.endpoint} did not "
                            f"free within {timeout_s}s.",
                            endpoint=self.endpoint,
                        )
                    )
                    break
                self._cv.wait(
                    timeout=0.5 if remaining is None else min(remaining, 0.5)
                )
            if self._dead is not None:
                err = WireError(
                    "transport",
                    f"pipeline channel to {self.endpoint} is down: "
                    f"{self._dead}",
                    endpoint=self.endpoint,
                )
                err.request_sent = True
                raise err
            self._inflight[key] = True
            if _obs._enabled:
                occupancy = sum(
                    1 for t, _s in self._inflight if t == tenant_id
                )
                _obs.histo(
                    "serve.client.inflight",
                    float(occupancy),
                    tenant=tenant_id,
                )
        try:
            with self._send_lock:
                if isinstance(payload, tuple):
                    send_frame_parts(self._sock, header, *payload)
                else:
                    send_frame(self._sock, header, payload)
        except OSError as e:
            with self._cv:
                self._inflight.pop(key, None)
            self._fail(e)
            err = WireError(
                "transport",
                f"pipelined {header.get('op')} to {self.endpoint} "
                f"failed: {e}",
                endpoint=self.endpoint,
            )
            err.request_sent = True
            raise err from e

    def wait_idle(
        self,
        tenant_id: str,
        state: "_ClientTenant",
        timeout_s: Optional[float],
    ) -> None:
        """Block until no frames for ``tenant_id`` are in flight, then
        fold its parked acks (caller holds ``state.lock``). Never
        raises: a timeout poisons the channel, which the fold turns
        into ``needs_resend``."""
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        with self._cv:
            while self._dead is None and any(
                t == tenant_id for t, _s in self._inflight
            ):
                remaining = (
                    None
                    if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    self._fail_locked(
                        WireError(
                            "request_timeout",
                            f"pipelined tail for tenant {tenant_id!r} was "
                            f"not acked within {timeout_s}s.",
                            endpoint=self.endpoint,
                        )
                    )
                    break
                self._cv.wait(
                    timeout=0.5 if remaining is None else min(remaining, 0.5)
                )
        self.fold_locked(tenant_id, state)

    def forget(self, tenant_id: str) -> None:
        """Drop every record of ``tenant_id`` (export/migration: the
        replay buffer travels; stale acks and window slots must not)."""
        with self._cv:
            self._pending.pop(tenant_id, None)
            self._dirty.discard(tenant_id)
            stale = [k for k in self._inflight if k[0] == tenant_id]
            for k in stale:
                del self._inflight[k]
            if stale:
                self._cv.notify_all()

    def close(self, timeout_s: float = 5.0) -> None:
        """Give in-flight frames a bounded grace to drain, then sever.
        Un-acked frames stay booked in their replay buffers — the safe
        state for a closing client (a future adopt replays them)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._dead is None and self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=min(remaining, 0.5))
            if self._dead is None:
                self._dead = ServeError(
                    "client_closed", "EvalClient is closed."
                )
            self._cv.notify_all()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2.0)


class EvalClient:
    """Wire client for one eval-service host. See module doc.

    ``address`` is ``"host:port"`` or a ``(host, port)`` tuple. All
    deadline knobs are validated eagerly (NaN/inf/non-positive raise
    ``ValueError`` before any socket exists).
    """

    def __init__(
        self,
        address: Any,
        *,
        request_timeout_s: Optional[float] = 30.0,
        connect_timeout_s: Optional[float] = 5.0,
        max_attempts: int = 5,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        max_in_flight: int = 8,
        breaker_threshold: int = 3,
        breaker_reset_s: float = 1.0,
        replay_capacity: int = 64,
        submit_buffer: int = 1,
        codec: Optional[str] = None,
        pipeline_depth: int = 1,
        local_transport: bool = True,
    ) -> None:
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        for knob, value in (
            ("request_timeout_s", request_timeout_s),
            ("connect_timeout_s", connect_timeout_s),
            ("backoff_base_s", backoff_base_s),
            ("backoff_cap_s", backoff_cap_s),
            ("breaker_reset_s", breaker_reset_s),
        ):
            try:
                _check_timeout_s(value)
            except ValueError as e:
                raise ValueError(f"{knob}: {e}") from None
        for knob, value, floor in (
            ("max_attempts", max_attempts, 1),
            ("max_in_flight", max_in_flight, 1),
            ("breaker_threshold", breaker_threshold, 1),
            ("replay_capacity", replay_capacity, 1),
            ("submit_buffer", submit_buffer, 1),
            ("pipeline_depth", pipeline_depth, 1),
        ):
            if not isinstance(value, int) or value < floor:
                raise ValueError(
                    f"{knob} must be an int >= {floor}, got {value!r}."
                )
        # wire-codec preference (ISSUE 12): "raw" never offers, "delta"
        # offers the lossless integer codec, "qblk" additionally offers
        # block-quantized f32 leaves (bounded error — an explicit opt-in).
        # None defers to TORCHEVAL_TPU_WIRE_CODEC (default raw). The
        # preference only OFFERS: encoding starts after the server
        # advertises support at attach, so a raw-only peer degrades the
        # wire to raw with no protocol error.
        from torcheval_tpu.utils.quant import wire_codec_default

        if codec is None:
            codec = wire_codec_default()
        if codec not in ("raw", "delta", "qblk"):
            raise ValueError(
                "codec must be one of 'raw', 'delta', 'qblk' (or None "
                f"for the TORCHEVAL_TPU_WIRE_CODEC default), got {codec!r}."
            )
        self._codec_pref = codec
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            try:
                self._addr: Tuple[str, int] = (host, int(port))
            except ValueError:
                raise ValueError(
                    f"address must be 'host:port' or (host, port), "
                    f"got {address!r}."
                ) from None
        else:
            host, port = address
            self._addr = (str(host), int(port))
        self.endpoint = f"{self._addr[0]}:{self._addr[1]}"
        self._request_timeout_s = request_timeout_s
        self._connect_timeout_s = connect_timeout_s
        self._max_attempts = max_attempts
        self._backoff_base_s = backoff_base_s
        self._backoff_cap_s = backoff_cap_s
        self._breaker_threshold = breaker_threshold
        self._breaker_reset_s = breaker_reset_s
        self.replay_capacity = replay_capacity
        # submit coalescing (ISSUE 11): >1 buffers this many booked
        # batches per tenant and ships them as ONE submit_many frame —
        # frame overhead (round trip, headers, archive directory)
        # amortizes over the group exactly like the daemon's coalesced
        # H2D amortizes transfers. Batches are booked into the replay
        # buffer at submit() time, so the reliability story is unchanged:
        # anything unsent or unacked is redelivered by replay + dedup.
        self.submit_buffer = min(submit_buffer, replay_capacity)
        # deferred-ack pipelining (ISSUE 18): >1 ASKS the server at
        # attach for a streamed-submit window this deep; the grant (the
        # min of both sides, PR 12 negotiation discipline) drives a
        # dedicated channel socket opened lazily on the first submit.
        # 1 keeps today's lock-step request-response wire.
        self.pipeline_depth = min(pipeline_depth, replay_capacity)
        # same-host fast path (ISSUE 18): hand submit payloads to an
        # in-process server directly instead of round-tripping the
        # loopback socket. Auto-selected per call; False forces TCP
        # (benchmarks measuring the socket path want the real wire).
        self._local_transport = bool(local_transport)
        self._pipeline_granted = 0
        self._pipeline_unsupported = False
        self._channel: Optional[_PipelinedChannel] = None
        self._channel_lock = threading.Lock()
        self._inflight = threading.BoundedSemaphore(max_in_flight)
        self._lock = threading.Lock()
        self._pool: List[socket.socket] = []
        self._closed = False
        self._breaker_failures = 0
        self._breaker_opened_at = 0.0
        self._breaker_probing = False
        self._tenants: Dict[str, _ClientTenant] = {}
        self._subscriptions: List[ObsSubscription] = []

    # ------------------------------------------------------------ transport
    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._closed:
                raise ServeError("client_closed", "EvalClient is closed.")
            if self._pool:
                return self._pool.pop()
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout_s
        )
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        return sock

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed:
                self._pool.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def close(self) -> None:
        # best-effort: ship any coalesced unsent tails first — a buffered
        # submit() returned True for these batches, so dropping them
        # silently on close would break the delivered-on-True contract.
        # A drain failure is swallowed (we are closing; the batches stay
        # booked in the replay buffer for a future migration/adopt).
        with self._lock:
            tenants = list(self._tenants.items())
        for tenant_id, state in tenants:
            try:
                with state.lock:
                    if (
                        state.sendbuf
                        and not state.migrated
                        and not state.needs_resend
                    ):
                        self._drain_sendbuf_locked(
                            tenant_id, state, _UNSET
                        )
            except (ServeError, WireError, OSError):
                pass
        with self._lock:
            self._closed = True
            pool, self._pool = self._pool, []
            subs, self._subscriptions = self._subscriptions, []
        with self._channel_lock:
            ch, self._channel = self._channel, None
        if ch is not None:
            # bounded grace for the in-flight tail; anything un-acked
            # stays booked in its replay buffer (adopt replays it)
            ch.close()
        for sub in subs:
            sub.stop()
        for sock in pool:
            try:
                sock.close()
            except OSError:
                pass

    def __enter__(self) -> "EvalClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------------- breaker
    def _breaker_gate(self) -> None:
        with self._lock:
            if self._breaker_failures < self._breaker_threshold:
                return
            if (
                time.monotonic() - self._breaker_opened_at
                >= self._breaker_reset_s
            ) and not self._breaker_probing:
                # half-open: exactly ONE probe goes to the socket; every
                # other caller keeps failing fast until it reports back
                self._breaker_probing = True
                return
        if _obs._enabled:
            _obs.counter(
                "serve.client.breaker", event="fastfail", endpoint=self.endpoint
            )
        raise WireError(
            "circuit_open",
            f"circuit to {self.endpoint} is open after "
            f"{self._breaker_threshold} consecutive transport failures; "
            f"failing fast for {self._breaker_reset_s}s.",
            endpoint=self.endpoint,
        )

    def _breaker_failure(self) -> None:
        with self._lock:
            self._breaker_probing = False
            self._breaker_failures += 1
            opened = self._breaker_failures == self._breaker_threshold
            if opened or (
                self._breaker_failures > self._breaker_threshold
            ):
                self._breaker_opened_at = time.monotonic()
        if opened and _obs._enabled:
            _obs.counter(
                "serve.client.breaker", event="open", endpoint=self.endpoint
            )

    def _breaker_success(self) -> None:
        with self._lock:
            self._breaker_probing = False
            self._breaker_failures = 0

    # ---------------------------------------------------------------- calls
    def _call(
        self,
        op: str,
        header: Dict[str, Any],
        payload: bytes = b"",
        *,
        timeout_s: Any = _UNSET,
        attempts: Optional[int] = None,
        ambiguity_box: Optional[dict] = None,
    ) -> Tuple[Dict[str, Any], bytes]:
        """One wire request with the full reliability stack (deadline,
        breaker, bounded in-flight, backoff retries). Safe to blind-retry
        by construction: submits are deduplicated by seq, attach/detach
        are idempotent (nonce / already-gone-counts-as-done), and every
        other op is a read. ``attempts`` overrides ``max_attempts`` for
        this call (health probes want to fail fast). ``ambiguity_box``,
        when given, has its ``"sent"`` entry incremented for every
        attempt that may have REACHED the server without an answer — a
        caller that must know whether an earlier try could have landed
        (submit's rollback logic) reads it."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        if timeout_s is _UNSET:
            timeout_s = self._request_timeout_s
        else:
            _check_timeout_s(timeout_s)
        max_attempts = self._max_attempts if attempts is None else attempts
        header = {"op": op, **header}
        delay_s = self._backoff_base_s
        for attempt in range(1, max_attempts + 1):
            self._breaker_gate()
            try:
                response = self._roundtrip(header, payload, timeout_s)
            except WireError as e:
                if ambiguity_box is not None and getattr(
                    e, "request_sent", False
                ):
                    # the request went out before the failure: the server
                    # may have processed it even though we got no answer
                    ambiguity_box["sent"] = ambiguity_box.get("sent", 0) + 1
                if e.reason == "protocol":
                    # the peer speaks something else; retrying cannot fix it
                    self._breaker_failure()
                    raise
                self._breaker_failure()
                if attempt == max_attempts:
                    raise
                delay_s = self._sleep_backoff(delay_s, e.reason)
                continue
            self._breaker_success()
            resp_header, resp_payload = response
            if resp_header.get("ok"):
                return resp_header, resp_payload
            err = decode_error(resp_header.get("error", {}))
            if (
                getattr(err, "retryable", False)
                and attempt < max_attempts
            ):
                delay_s = self._sleep_backoff(
                    delay_s, getattr(err, "reason", "remote")
                )
                continue
            raise err
        raise AssertionError("unreachable")  # pragma: no cover

    def _roundtrip(
        self,
        header: Dict[str, Any],
        payload: bytes,
        timeout_s: Optional[float],
    ) -> Tuple[Dict[str, Any], bytes]:
        if self._local_transport and header.get("op") in (
            "submit",
            "submit_many",
        ):
            server = local_server(self.endpoint)
            if server is not None:
                # same-host fast path: the payload (or the staging slot
                # it is assembled into) IS the buffer the daemon
                # decodes — no socket, no frame codec, no copy pair.
                # Structured rejects come back as the same ok=False
                # response frames, so the caller's retry/un-book logic
                # is transport-agnostic.
                with self._inflight:
                    try:
                        return server.local_request(dict(header), payload)
                    except OSError as e:
                        err = WireError(
                            "transport",
                            f"local transport to {self.endpoint} "
                            f"failed: {e}",
                            endpoint=self.endpoint,
                        )
                        # the dispatch may have run before a partition
                        # tripped; ambiguous, like any failed send
                        err.request_sent = True
                        raise err from e
        with self._inflight:
            try:
                sock = self._checkout()
            except OSError as e:
                err = WireError(
                    "transport",
                    f"cannot connect to {self.endpoint}: {e}",
                    endpoint=self.endpoint,
                )
                err.request_sent = False  # never left this process
                raise err from e
            try:
                sock.settimeout(timeout_s)
                if isinstance(payload, tuple):
                    # scatter-gather payload (parts, total): array data
                    # goes straight from its owning buffers to the kernel
                    send_frame_parts(sock, header, *payload)
                else:
                    send_frame(sock, header, payload)
                frame = recv_frame(sock)
            except socket.timeout:
                self._discard(sock)
                err = WireError(
                    "request_timeout",
                    f"{header.get('op')} to {self.endpoint} produced no "
                    f"response within {timeout_s}s.",
                    endpoint=self.endpoint,
                )
                err.request_sent = True
                raise err from None
            except OSError as e:
                self._discard(sock)
                err = WireError(
                    "transport",
                    f"{header.get('op')} to {self.endpoint} failed: {e}",
                    endpoint=self.endpoint,
                )
                # a failed send MAY still have delivered bytes the server
                # acted on; only a connect failure is unambiguous
                err.request_sent = True
                raise err from e
            except WireError as e:
                self._discard(sock)
                e.request_sent = True
                raise
            if frame is None:
                self._discard(sock)
                err = WireError(
                    "transport",
                    f"{self.endpoint} closed the connection before "
                    "answering.",
                    endpoint=self.endpoint,
                )
                err.request_sent = True
                raise err
            self._checkin(sock)
            return frame

    @staticmethod
    def _discard(sock: socket.socket) -> None:
        try:
            sock.close()
        except OSError:
            pass

    def _sleep_backoff(self, delay_s: float, reason: str) -> float:
        if _obs._enabled:
            _obs.counter("serve.client.retries", reason=reason)
        time.sleep(min(delay_s, self._backoff_cap_s) * (0.5 + random.random()))
        return delay_s * 2

    @staticmethod
    def _account_payload(codec: str, np_args_groups, encoded: int) -> None:
        """Raw-vs-encoded byte counters per codec: the pair makes the
        wire's compression ratio (and the raw==encoded invariant of the
        raw codec) readable straight off the client registry."""
        if not _obs._enabled:
            return
        raw = float(
            sum(
                int(a.nbytes)
                for args in np_args_groups
                for a in args
            )
        )
        _obs.counter("serve.client.payload_raw_bytes", raw, codec=codec)
        _obs.counter(
            "serve.client.payload_bytes", float(encoded), codec=codec
        )

    def _submit_header(
        self, tenant_id: str, codec: str, **fields: Any
    ) -> Dict[str, Any]:
        header = {"tenant": tenant_id, **fields}
        if codec != "raw":
            header["codec"] = codec
        return header

    # ----------------------------------------------------------- tenant api
    def attach(
        self,
        tenant_id: str,
        spec: Dict[str, Any],
        *,
        nan_policy: Optional[str] = None,
        watchdog_timeout_s: Optional[float] = None,
        step_timeout_s: Optional[float] = None,
        queue_capacity: Optional[int] = None,
        resume: Optional[str] = None,
        window_chunks: Optional[int] = None,
        approx=None,
        slices=None,
        timeout_s: Any = _UNSET,
    ) -> Dict[str, Any]:
        """Attach ``tenant_id`` with a wire metric spec (see
        :func:`metric_spec`). Returns ``{"last_seq": durable_watermark}``
        — 0 for a fresh tenant, the checkpoint's acked watermark for a
        resumed one. Admission failures raise the same structured
        :class:`AdmissionError` a local ``attach`` would. The request
        carries a one-shot nonce so a blind retry after an ambiguous
        failure (our attach landed, the ack did not) is recognized
        server-side and answered with the ORIGINAL success instead of
        ``duplicate_tenant`` — attach is idempotent per call, like
        submit. ``slices`` threads the per-cohort config (ISSUE 15:
        ``True`` / capacity int / ``{"capacity":, "curve_bucket_bits":}``;
        ISSUE 17 adds ``"mesh_axis": str`` — a plain axis-name string the
        DAEMON turns into a slice-axis-sharded collection over its own
        local devices, so no device handle ever crosses the wire) — every
        ``submit`` for a sliced tenant must then carry the ``slice_ids``
        integer column as its FIRST argument, and ``compute`` returns
        per-slice ``{"slice_ids": ..., "values": ...}`` results per
        member."""
        req = {
            "tenant": tenant_id,
            "spec": spec,
            "nonce": uuid.uuid4().hex,
            "nan_policy": nan_policy,
            "watchdog_timeout_s": watchdog_timeout_s,
            "step_timeout_s": step_timeout_s,
            "queue_capacity": queue_capacity,
            "resume": resume,
            "window_chunks": window_chunks,
            "approx": approx,
            "slices": slices,
        }
        if self._codec_pref != "raw":
            # capability exchange: qblk implies the lossless delta codec
            # as a second choice, so a delta-only server still compresses
            req["codecs"] = (
                ["qblk", "delta"]
                if self._codec_pref == "qblk"
                else ["delta"]
            )
        if self.pipeline_depth >= 2:
            # same handshake discipline as the codec offer: the server
            # grants min(ask, its own cap) in the response, an old
            # server ignores the field entirely — either way the wire
            # degrades to lock-step with no protocol error
            req["pipeline"] = self.pipeline_depth
        header, _ = self._call("attach", req, timeout_s=timeout_s)
        last_seq = int(header.get("last_seq", 0))
        codec = str(header.get("codec") or "raw")
        granted = header.get("pipeline")
        if (
            isinstance(granted, int)
            and not isinstance(granted, bool)
            and granted >= 2
        ):
            with self._channel_lock:
                self._pipeline_granted = max(
                    self._pipeline_granted, granted
                )
        with self._lock:
            self._tenants[tenant_id] = _ClientTenant(last_seq, codec)
        return {"last_seq": last_seq, "codec": codec}

    def _tenant_state(self, tenant_id: str) -> _ClientTenant:
        with self._lock:
            state = self._tenants.get(tenant_id)
        if state is None:
            raise ServeError(
                "unknown_tenant",
                f"tenant {tenant_id!r} is not attached through this client.",
            )
        return state

    # ------------------------------------------------------ pipeline channel
    def _pipeline_channel(
        self, timeout_s: Any
    ) -> Optional[_PipelinedChannel]:
        """The live deferred-ack channel, opening one lazily. ``None``
        means this call runs lock-step: pipelining was never granted at
        attach, the peer rejected ``pipeline_open`` (an old or
        pipeline-disabled server — remembered, never re-probed), the
        endpoint is served in-process (the local transport already
        skips the round trip a window would overlap), or the open
        itself hit transport trouble (the lock-step path owns the
        breaker/retry story)."""
        if self._pipeline_granted < 2 or self._pipeline_unsupported:
            return None
        if (
            self._local_transport
            and local_server(self.endpoint) is not None
        ):
            return None
        with self._channel_lock:
            old = self._channel
            if old is not None and old.alive:
                return old
            # a dead channel STAYS registered until a live replacement
            # exists: its parked acks and dirty flags must keep feeding
            # sync-point folds if this open attempt fails
            try:
                sock = socket.create_connection(
                    self._addr, timeout=self._connect_timeout_s
                )
            except OSError:
                return None
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            try:
                sock.settimeout(self._effective_timeout(timeout_s))
                send_frame(
                    sock,
                    {
                        "op": "pipeline_open",
                        "depth": self._pipeline_granted,
                    },
                )
                frame = recv_frame(sock)
            except (OSError, WireError):
                self._discard(sock)
                return None
            if frame is None:
                self._discard(sock)
                return None
            header, _payload = frame
            if not header.get("ok"):
                self._discard(sock)
                err = decode_error(header.get("error", {}))
                if (
                    isinstance(err, WireError)
                    and getattr(err, "reason", None) == "protocol"
                ):
                    # PR 12 discipline: an old peer degrades the wire to
                    # lock-step for the client's lifetime, never breaks
                    self._pipeline_unsupported = True
                return None
            try:
                depth = int(header.get("depth", 0))
            except (TypeError, ValueError):
                depth = 0
            if depth < 2:
                self._discard(sock)
                self._pipeline_unsupported = True
                return None
            sock.settimeout(None)  # acks arrive on the server's schedule
            ch = _PipelinedChannel(sock, depth, self.endpoint)
            if old is not None:
                # carry the dead channel's unfolded bookkeeping over:
                # parked acks and needs-resend flags must survive the
                # swap, or a tenant that never submits again (compute
                # only) would miss its error acks at the sync point
                with old._cv:
                    pend, old._pending = old._pending, {}
                    dirty, old._dirty = set(old._dirty), set()
                with ch._cv:
                    for t, acks in pend.items():
                        ch._pending.setdefault(t, []).extend(acks)
                    ch._dirty |= dirty
            self._channel = ch
            return ch

    def _channel_quiesce_locked(
        self, tenant_id: str, state: _ClientTenant, timeout_s: Any
    ) -> None:
        """Drain + fold this tenant's pipelined in-flight tail (no-op
        without a channel; caller holds ``state.lock``). Leaves
        ``needs_resend`` set when an ack reported an error or the
        channel died — the caller's resend path settles delivery."""
        with self._channel_lock:
            ch = self._channel
        if ch is not None:
            ch.wait_idle(
                tenant_id, state, self._effective_timeout(timeout_s)
            )

    def submit(
        self, tenant_id: str, *args: Any, timeout_s: Any = _UNSET
    ) -> bool:
        """Submit one update batch. Assigns the next sequence number,
        holds the batch in the bounded replay buffer until it is durable,
        and retries transparently (dedup makes resends exactly-once).
        Returns ``True`` if this call's send was applied, ``False`` if
        the server had it already (a prior ambiguous attempt landed).
        Under ``submit_buffer > 1`` or an active pipeline channel the
        return is always ``True`` (the batch is BOOKED; the server's
        per-batch dedup verdicts ride the coalesced or deferred ack and
        are not reported per call) — callers that need the per-batch
        applied signal use an unbuffered lock-step client."""
        state = self._tenant_state(tenant_id)
        np_args = tuple(np.asarray(a) for a in args)
        with state.lock:
            if state.migrated:
                raise ServeError(
                    "tenant_migrated",
                    f"tenant {tenant_id!r} was migrated off this host "
                    "mid-call; re-route and resubmit (the batch was not "
                    "booked).",
                )
            ch = self._pipeline_channel(timeout_s)
            try:
                if ch is not None:
                    # fold parked acks first: an error ack must flip
                    # needs_resend BEFORE this call sequences past it
                    ch.fold_locked(tenant_id, state)
                if state.needs_resend:
                    self._channel_quiesce_locked(
                        tenant_id, state, timeout_s
                    )
                    self._resend_locked(tenant_id, state, timeout_s)
                if len(state.replay) >= self.replay_capacity:
                    # replay valve: drain the pipelined tail first (its
                    # acks alone may free the buffer), then checkpoint
                    # server-side to advance the durable watermark and
                    # prune — the buffer stays bounded without ever
                    # dropping a non-durable batch
                    self._channel_quiesce_locked(
                        tenant_id, state, timeout_s
                    )
                    if state.needs_resend:
                        self._resend_locked(tenant_id, state, timeout_s)
                    if len(state.replay) >= self.replay_capacity:
                        self._flush_locked(tenant_id, state, timeout_s)
            except (WireError, ServeError) as e:
                # pre-booking failure: earlier BOOKED entries redeliver
                # through replay, but THIS call's batch was never booked —
                # a batch_booked=True leaking out of the flush's internal
                # drain would make the router skip resubmitting it
                e.batch_booked = False
                raise
            if self.submit_buffer > 1:
                return self._buffered_submit_locked(
                    tenant_id, state, np_args, timeout_s
                )
            # marshal BEFORE booking: an unmarshalable or over-limit
            # argument must fail this call cleanly, not leave a poison
            # entry in the replay buffer that every future resend and
            # migration chokes on (the server would drop an oversize
            # frame without answering, which reads as host death)
            spec, blob = pack_tree(list(np_args), codec=state.codec)
            self._account_payload(state.codec, [np_args], len(blob))
            from torcheval_tpu.serve.wire import _MAX_PAYLOAD_BYTES

            if len(blob) > _MAX_PAYLOAD_BYTES:
                raise WireError(
                    "protocol",
                    f"batch payload is {len(blob)} bytes, over the "
                    f"{_MAX_PAYLOAD_BYTES}-byte wire limit; split the "
                    "batch.",
                    endpoint=self.endpoint,
                )
            seq = state.next_seq
            state.next_seq += 1
            state.replay.append((seq, np_args))
            if ch is not None:
                wire_header = self._submit_header(
                    tenant_id, state.codec, seq=seq, args=spec
                )
                wire_header["op"] = "submit"
                # the bound the server's gapless admission blocks under
                wire_header["timeout"] = self._effective_timeout(
                    timeout_s
                )
                try:
                    ch.send(
                        tenant_id,
                        state,
                        wire_header,
                        blob,
                        self._effective_timeout(timeout_s),
                    )
                except WireError as e:
                    # ambiguous, exactly like the lock-step transport
                    # branch: the frame may be on the wire — booked +
                    # needs_resend settle it at the next call
                    state.needs_resend = True
                    e.batch_booked = True
                    raise
                # streamed: the ack rides back asynchronously and folds
                # at the next submit/flush/compute; True means BOOKED
                return True
            ambiguity: dict = {}
            try:
                header, _ = self._call(
                    "submit",
                    self._submit_header(
                        tenant_id, state.codec, seq=seq, args=spec
                    ),
                    blob,
                    timeout_s=timeout_s,
                    ambiguity_box=ambiguity,
                )
            except WireError as e:
                # ambiguous: the batch may or may not have landed. It
                # STAYS booked in the replay buffer under its seq — a
                # migration replays it, dedup absorbs the overlap. Mark
                # the error so the router knows delivery is now the
                # replay buffer's job and must NOT resubmit the batch
                # under a fresh seq (that would double-apply it). A
                # direct (router-less) caller that keeps submitting is
                # covered by needs_resend: the next call re-delivers this
                # booked tail before any new seq can advance the daemon
                # watermark past the hole.
                state.needs_resend = True
                e.batch_booked = True
                raise
            except ServeError as e:
                if not ambiguity.get("sent"):
                    # a STRUCTURED reject with NO earlier ambiguous send:
                    # the daemon saw this seq exactly once and did not
                    # admit it (shed after retries, quarantine,
                    # draining) — un-book it so the replay buffer never
                    # re-applies a rejected batch
                    state.replay.pop()
                    state.next_seq = seq
                else:
                    # an earlier attempt of this seq MAY have been
                    # admitted before its ack was lost; rolling the seq
                    # back would hand it to the NEXT batch, which the
                    # daemon would then dedup away (silent loss). Keep
                    # the booking: replay/dedup settle it exactly-once —
                    # and flag the resend catch-up exactly like the
                    # transport branch, or a later seq could advance the
                    # daemon watermark past this possibly-unapplied hole.
                    state.needs_resend = True
                    e.batch_booked = True
                raise
            state.durable_seq = max(
                state.durable_seq, int(header.get("acked_seq", 0))
            )
            self._prune_locked(state)
            return bool(header.get("applied", True))

    def _buffered_submit_locked(
        self,
        tenant_id: str,
        state: _ClientTenant,
        np_args: tuple,
        timeout_s: Any,
    ) -> bool:
        """Book one batch into the replay buffer AND the coalesced send
        tail; ship the tail as one ``submit_many`` frame when it reaches
        ``submit_buffer`` batches (or would overflow the frame limit).
        Returns ``True`` — the batch is booked; any dedup of an earlier
        ambiguous landing happens server-side when the frame ships."""
        from torcheval_tpu.serve.wire import _MAX_PAYLOAD_BYTES

        for a in np_args:
            if a.dtype.hasobject:
                # validate at booking time: a poison entry must fail THIS
                # call, never lurk in the replay buffer
                raise WireError(
                    "protocol",
                    "cannot marshal object arrays over the eval wire.",
                    endpoint=self.endpoint,
                )
        nbytes = sum(int(a.nbytes) for a in np_args) + 4096
        if nbytes > _MAX_PAYLOAD_BYTES:
            raise WireError(
                "protocol",
                f"batch payload is ~{nbytes} bytes, over the "
                f"{_MAX_PAYLOAD_BYTES}-byte wire limit; split the batch.",
                endpoint=self.endpoint,
            )
        pending = sum(
            sum(int(a.nbytes) for a in args) + 4096
            for _seq, args in state.sendbuf
        )
        if state.sendbuf and pending + nbytes > _MAX_PAYLOAD_BYTES:
            try:
                self._drain_sendbuf_locked(tenant_id, state, timeout_s)
            except (WireError, ServeError) as e:
                # the drained tail is booked (replay covers it); THIS
                # batch is not — the caller must resubmit it
                e.batch_booked = False
                raise
        seq = state.next_seq
        state.next_seq += 1
        state.replay.append((seq, np_args))
        state.sendbuf.append((seq, np_args))
        if len(state.sendbuf) >= self.submit_buffer:
            self._drain_sendbuf_locked(tenant_id, state, timeout_s)
        return True

    def _drain_sendbuf_locked(
        self, tenant_id: str, state: _ClientTenant, timeout_s: Any
    ) -> None:
        """Ship the booked-but-unsent tail as ONE ``submit_many`` frame.
        On any failure the whole group stays booked in the replay buffer
        (``needs_resend``): redelivery in seq order + server dedup settle
        whichever prefix actually landed, exactly once."""
        if not state.sendbuf:
            return
        take, state.sendbuf = state.sendbuf, []
        seqs = [seq for seq, _args in take]
        spec, parts, total = pack_tree_parts(
            [list(args) for _seq, args in take], codec=state.codec
        )
        self._account_payload(
            state.codec, [args for _seq, args in take], total
        )
        ch = self._pipeline_channel(timeout_s)
        if ch is not None:
            wire_header = self._submit_header(
                tenant_id, state.codec, seqs=seqs, args=spec
            )
            wire_header["op"] = "submit_many"
            wire_header["timeout"] = self._effective_timeout(timeout_s)
            try:
                ch.send(
                    tenant_id,
                    state,
                    wire_header,
                    (parts, total),
                    self._effective_timeout(timeout_s),
                )
            except WireError as e:
                state.needs_resend = True
                e.batch_booked = True
                raise
            return  # the deferred ack folds at the next sync point
        try:
            header, _ = self._call(
                "submit_many",
                self._submit_header(
                    tenant_id, state.codec, seqs=seqs, args=spec
                ),
                (parts, total),
                timeout_s=timeout_s,
            )
        except (WireError, ServeError) as e:
            state.needs_resend = True
            e.batch_booked = True
            raise
        state.durable_seq = max(
            state.durable_seq, int(header.get("acked_seq", 0))
        )
        self._prune_locked(state)

    def _drain_for(self, tenant_id: str, timeout_s: Any) -> None:
        """Deliver any coalesced booked-but-undelivered tail before an op
        whose result must reflect every prior ``submit``
        (compute/sync_compute/detach). The needs-resend check comes
        FIRST: a failed coalesced drain empties the send tail but leaves
        its batches booked in the replay buffer, and those must redeliver
        too — a ``submit()`` that returned ``True`` may never silently
        miss a compute. Buffered (``submit_buffer > 1``) and pipelined
        (a channel was opened) clients only: both return ``True`` for
        batches still on their way, so the sync point must land them.
        The unbuffered lock-step client's long-standing semantics — a
        FAILED submit's hole redelivers at the next submit/flush, not
        at compute — stay exactly as they were."""
        with self._channel_lock:
            pipelined = self._channel is not None
        if self.submit_buffer <= 1 and not pipelined:
            return
        with self._lock:
            state = self._tenants.get(tenant_id)
        if state is None:
            return
        with state.lock:
            if state.migrated:
                return
            self._channel_quiesce_locked(tenant_id, state, timeout_s)
            if state.needs_resend:
                self._resend_locked(tenant_id, state, timeout_s)
            if state.sendbuf:
                self._drain_sendbuf_locked(tenant_id, state, timeout_s)
                # a pipelined drain only STREAMS the tail; land it
                self._channel_quiesce_locked(tenant_id, state, timeout_s)
                if state.needs_resend:
                    self._resend_locked(tenant_id, state, timeout_s)

    def flush(self, tenant_id: str, *, timeout_s: Any = _UNSET) -> dict:
        """Checkpoint the tenant server-side (no eviction), advance the
        durable watermark, prune the replay buffer. Returns
        ``{"path": ..., "acked_seq": ...}``."""
        state = self._tenant_state(tenant_id)
        with state.lock:
            if state.migrated:
                raise ServeError(
                    "tenant_migrated",
                    f"tenant {tenant_id!r} was migrated off this host "
                    "mid-call; re-route.",
                )
            self._channel_quiesce_locked(tenant_id, state, timeout_s)
            if state.needs_resend:
                self._resend_locked(tenant_id, state, timeout_s)
            return self._flush_locked(tenant_id, state, timeout_s)

    def _send_replay_entries(
        self, tenant_id: str, state: _ClientTenant, timeout_s: Any
    ) -> int:
        """Deliver every current replay entry in seq order under the
        caller-held ``state.lock`` (the daemon dedups any that already
        landed), folding acked durable watermarks in and pruning. The
        ONE loop behind resend catch-up and migration replay — fixes to
        its semantics cannot diverge between the two. Returns the number
        of entries sent."""
        sent = 0
        for seq, np_args in list(state.replay):
            spec, blob = pack_tree(list(np_args), codec=state.codec)
            self._account_payload(state.codec, [np_args], len(blob))
            header, _ = self._call(
                "submit",
                self._submit_header(
                    tenant_id, state.codec, seq=seq, args=spec
                ),
                blob,
                timeout_s=timeout_s,
            )
            sent += 1
            state.durable_seq = max(
                state.durable_seq, int(header.get("acked_seq", 0))
            )
        self._prune_locked(state)
        return sent

    def _resend_locked(
        self, tenant_id: str, state: _ClientTenant, timeout_s: Any
    ) -> None:
        """Re-deliver the booked tail a failed submit left behind,
        clearing the hole. Raises (flag intact) if the host is still
        unreachable — nothing new may be sequenced past the hole until
        it closes. Coalesced unsent entries are already booked in the
        replay buffer, so dropping the send tail and replaying covers
        them in seq order."""
        state.sendbuf.clear()
        self._send_replay_entries(tenant_id, state, timeout_s)
        state.needs_resend = False

    def _flush_locked(
        self, tenant_id: str, state: _ClientTenant, timeout_s: Any
    ) -> dict:
        # the durable watermark a flush advances must cover the booked
        # tail: ship any coalesced unsent entries, then land the
        # pipelined in-flight window (gapless admission keeps pruning
        # safe regardless — the server watermark can never pass a hole
        # — but the replay-valve caller needs the watermark to MOVE)
        self._drain_sendbuf_locked(tenant_id, state, timeout_s)
        self._channel_quiesce_locked(tenant_id, state, timeout_s)
        if state.needs_resend:
            self._resend_locked(tenant_id, state, timeout_s)
        header, _ = self._call(
            "flush",
            {
                "tenant": tenant_id,
                "timeout": self._effective_timeout(timeout_s),
            },
            timeout_s=timeout_s,
        )
        state.durable_seq = max(
            state.durable_seq, int(header.get("acked_seq", 0))
        )
        self._prune_locked(state)
        return {"path": header.get("path"), "acked_seq": state.durable_seq}

    @staticmethod
    def _prune_locked(state: _ClientTenant) -> None:
        while state.replay and state.replay[0][0] <= state.durable_seq:
            state.replay.popleft()

    def _effective_timeout(self, timeout_s: Any) -> Optional[float]:
        """The deadline a request actually runs under — forwarded to the
        daemon side so its promise wait is bounded by the same budget the
        socket is (otherwise each client retry would park one more
        handler thread on an unbounded wait)."""
        return (
            self._request_timeout_s if timeout_s is _UNSET else timeout_s
        )

    def compute(self, tenant_id: str, *, timeout_s: Any = _UNSET) -> Any:
        self._drain_for(tenant_id, timeout_s)
        header, payload = self._call(
            "compute",
            {
                "tenant": tenant_id,
                "timeout": self._effective_timeout(timeout_s),
            },
            timeout_s=timeout_s,
        )
        return unpack_tree(header["result"], payload)

    def sync_compute(
        self,
        tenant_id: str,
        *,
        sync_timeout_s: Optional[float] = None,
        on_failure: str = "raise",
        timeout_s: Any = _UNSET,
    ) -> Any:
        """``TenantHandle.sync_compute`` over the wire: ``sync_timeout_s``
        bounds the daemon-side collective rounds (the PR 5 contract);
        ``timeout_s`` bounds this wire request."""
        self._drain_for(tenant_id, timeout_s)
        header, payload = self._call(
            "sync_compute",
            {
                "tenant": tenant_id,
                "timeout_s": sync_timeout_s,
                "on_failure": on_failure,
                "timeout": self._effective_timeout(timeout_s),
            },
            timeout_s=timeout_s,
        )
        return unpack_tree(header["result"], payload)

    def detach(
        self,
        tenant_id: str,
        *,
        checkpoint: bool = False,
        timeout_s: Any = _UNSET,
    ) -> Optional[str]:
        """Detach over the wire. Idempotent: a retry of a detach whose
        ack was lost finds the tenant already gone (``unknown_tenant``)
        and counts that as success — the caller asked for the tenant to
        be detached, and it is (a checkpoint path from the first landing
        is lost with the ack in that corner; ``resilience.
        latest_checkpoint(<root>/<tenant>)`` recovers it)."""
        self._drain_for(tenant_id, timeout_s)
        try:
            header, _ = self._call(
                "detach",
                {
                    "tenant": tenant_id,
                    "checkpoint": checkpoint,
                    "timeout": self._effective_timeout(timeout_s),
                },
                timeout_s=timeout_s,
            )
        except ServeError as e:
            if isinstance(e, WireError) or e.reason != "unknown_tenant":
                raise
            header = {}
        with self._lock:
            self._tenants.pop(tenant_id, None)
        return header.get("checkpoint")

    # ---------------------------------------------------------- cluster api
    def health(
        self, *, timeout_s: Any = _UNSET, attempts: Optional[int] = None
    ) -> Dict[str, Any]:
        """The host's ``daemon.health()`` snapshot. ``attempts`` caps the
        retry budget for this probe (a failure DETECTOR wants to fail
        fast, not ride the full backoff ladder)."""
        header, _ = self._call(
            "health", {}, timeout_s=timeout_s, attempts=attempts
        )
        return header["health"]

    def snapshot(self, *, timeout_s: Any = _UNSET) -> Dict[str, Any]:
        """The host's obs registry snapshot + Chrome trace (flight-record
        collection for drills and dashboards)."""
        header, payload = self._call("snapshot", {}, timeout_s=timeout_s)
        return unpack_tree(header["result"], payload)

    def load_report(self, *, timeout_s: Any = _UNSET) -> Dict[str, Any]:
        """The host's structured ``daemon.load_report()`` (schema 1) over
        a dedicated cheap wire op — the router rebalancer's pull path
        when no obs push stream is subscribed (ISSUE 19). An old server
        that predates the op rejects it as ``WireError("protocol")``;
        degrade to the ``health()`` embed (same payload, heavier probe)
        instead of failing — mixed versions degrade, never break."""
        try:
            header, _ = self._call("load_report", {}, timeout_s=timeout_s)
        except WireError as e:
            if e.reason != "protocol":
                raise
            return self.health(timeout_s=timeout_s)["load_report"]
        return header["load_report"]

    def list_tenants(
        self,
        *,
        timeout_s: Any = _UNSET,
        attempts: Optional[int] = None,
    ) -> Dict[str, Any]:
        """The host's attached-tenant directory — per tenant: ``status``,
        ``last_seq``, ``durable_seq``, plus the attach-time ``spec`` and
        ``knobs`` the server recorded (ISSUE 20). This is the recovering
        router's reconciliation pull: journal replay names the tenants it
        EXPECTS, this op names the tenants each host actually HOLDS, and
        the diff drives adopt / re-place / orphan adoption. An old server
        rejects the op as ``WireError("protocol")``; degrade to the
        ``health()`` per-tenant fold — same status + watermarks, no
        spec/knobs (orphans on old hosts stay unadoptable, a degradation
        not a break)."""
        try:
            header, _ = self._call(
                "list_tenants", {}, timeout_s=timeout_s, attempts=attempts
            )
        except WireError as e:
            if e.reason != "protocol":
                raise
            tenants = self.health(
                timeout_s=timeout_s, attempts=attempts
            ).get("tenants", {})
            return {
                tid: {
                    "status": info.get("status"),
                    "last_seq": info.get("last_seq", 0),
                    "durable_seq": info.get("durable_seq", 0),
                }
                for tid, info in tenants.items()
            }
        return header["tenants"]

    # ------------------------------------------------------------ obs stream
    def subscribe_obs(
        self,
        interval_s: float = 1.0,
        *,
        on_push: Optional[Any] = None,
        fallback: str = "poll",
    ) -> ObsSubscription:
        """Subscribe to the host's obs push channel (ISSUE 16).

        Opens a DEDICATED socket (outside the request pool — pushes are
        server-paced and must not occupy a pooled request slot), sends
        ``subscribe_obs``, and spawns a reader thread delivering each
        ``obs_push`` frame (registry delta + timeline events +
        ``load_report``) to ``on_push`` and :attr:`ObsSubscription.last`.

        An old server rejects the op with ``WireError("protocol")`` —
        never retried, never a failover trigger — and with
        ``fallback="poll"`` (default) the subscription degrades to
        polling ``health()`` on the same cadence (``mode == "poll"``).
        ``fallback="raise"`` surfaces the protocol error instead. The
        subscription is registered with this client and stopped by
        ``close()``."""
        from torcheval_tpu.metrics.toolkit import _check_timeout_s

        _check_timeout_s(interval_s)
        if fallback not in ("poll", "raise"):
            raise ValueError(
                f"fallback must be 'poll' or 'raise', got {fallback!r}."
            )
        with self._lock:
            if self._closed:
                raise ServeError("client_closed", "EvalClient is closed.")
        sub = ObsSubscription(self.endpoint, float(interval_s), on_push)
        try:
            sock = socket.create_connection(
                self._addr, timeout=self._connect_timeout_s
            )
        except OSError as e:
            raise WireError(
                "transport",
                f"cannot connect to {self.endpoint} for obs stream: {e}",
                endpoint=self.endpoint,
            ) from e
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        accepted = False
        try:
            sock.settimeout(self._request_timeout_s)
            send_frame(sock, {"op": "subscribe_obs", "interval_s": interval_s})
            frame = recv_frame(sock)
            if frame is None:
                raise WireError(
                    "transport",
                    f"{self.endpoint} closed the connection before "
                    "answering subscribe_obs.",
                    endpoint=self.endpoint,
                )
            header, _payload = frame
            if header.get("ok"):
                accepted = True
            else:
                err = decode_error(header.get("error", {}))
                if (
                    isinstance(err, WireError)
                    and getattr(err, "reason", None) == "protocol"
                    and fallback == "poll"
                ):
                    # PR 12 discipline: an old peer degrades, never breaks
                    accepted = False
                else:
                    raise err
        except socket.timeout:
            self._discard(sock)
            raise WireError(
                "request_timeout",
                f"subscribe_obs to {self.endpoint} produced no response "
                f"within {self._request_timeout_s}s.",
                endpoint=self.endpoint,
            ) from None
        except OSError as e:
            self._discard(sock)
            raise WireError(
                "transport",
                f"subscribe_obs to {self.endpoint} failed: {e}",
                endpoint=self.endpoint,
            ) from e
        except BaseException:
            self._discard(sock)
            raise
        if accepted:
            sub.mode = "push"
            sub._sock = sock
            sock.settimeout(None)  # pushes arrive on the server's timer
            sub._thread = threading.Thread(
                target=self._obs_read_loop,
                args=(sub, sock),
                name="torcheval-tpu-obs-subscriber",
                daemon=True,
            )
        else:
            sub.mode = "poll"
            self._discard(sock)  # the poller uses the request pool
            sub._thread = threading.Thread(
                target=self._obs_poll_loop,
                args=(sub,),
                name="torcheval-tpu-obs-poller",
                daemon=True,
            )
        with self._lock:
            self._subscriptions.append(sub)
        sub._thread.start()
        return sub

    @staticmethod
    def _obs_read_loop(sub: ObsSubscription, sock: socket.socket) -> None:
        while not sub._stop.is_set():
            try:
                frame = recv_frame(sock)
            except (OSError, WireError):
                break  # host died or stop() severed the socket
            if frame is None:
                break  # server closed: final flush already delivered
            header, _payload = frame
            if header.get("op") == "obs_push":
                sub._record(header)
        try:
            sock.close()
        except OSError:
            pass

    def _obs_poll_loop(self, sub: ObsSubscription) -> None:
        while not sub._stop.wait(sub.interval_s):
            try:
                health = self.health(attempts=1)
            except (ServeError, WireError, OSError):
                if self._closed:
                    break
                continue  # keep polling; the router judges staleness
            sub._record(
                {
                    "op": "obs_poll",
                    "endpoint": self.endpoint,
                    "load_report": health.get("load_report"),
                    "health": health,
                }
            )

    def drain(self, *, timeout_s: Any = _UNSET) -> Dict[str, Optional[str]]:
        """Ask the host to drain (evict-and-checkpoint every tenant).
        Returns ``{tenant_id: checkpoint_path}``."""
        header, _ = self._call(
            "drain",
            {"timeout": self._effective_timeout(timeout_s)},
            timeout_s=timeout_s,
        )
        return dict(header.get("tenants", {}))

    # ------------------------------------------------- migration bookkeeping
    def export_tenant(self, tenant_id: str) -> Dict[str, Any]:
        """Detach this client's local wire state for ``tenant_id`` (seqs +
        replay buffer) so the router can carry it to another host. Purely
        local: works when the host is dead."""
        with self._lock:
            state = self._tenants.pop(tenant_id, None)
        if state is None:
            raise ServeError(
                "unknown_tenant",
                f"tenant {tenant_id!r} is not attached through this client.",
            )
        with self._channel_lock:
            ch = self._channel
        with state.lock:
            state.migrated = True
            if ch is not None:
                # parked acks tighten the exported watermark (less to
                # replay); then drop the channel's window slots so a
                # deep un-acked tail cannot hold the window hostage —
                # the tail is booked in the replay buffer and the NEW
                # host's adopt replays it (old-host acks are moot)
                ch.fold_locked(tenant_id, state)
                ch.forget(tenant_id)
            # coalesced unsent entries are booked in the replay buffer,
            # so the export carries them; the new host's replay delivers
            state.sendbuf.clear()
            return {
                "next_seq": state.next_seq,
                "durable_seq": state.durable_seq,
                "replay": list(state.replay),
            }

    def drop_tenant(
        self,
        tenant_id: str,
        *,
        checkpoint: bool = False,
        timeout_s: Any = _UNSET,
    ) -> Optional[str]:
        """Server-side detach WITHOUT local wire state (ISSUE 19: a
        rebalance move exports the wire state first — ``detach`` would
        raise client-side ``unknown_tenant`` before ever reaching the
        host, yet the source daemon's attach record must still be
        released or the moved tenant keeps a capacity slot and its
        queue-load signal forever). ``checkpoint=False`` by default: the
        move's own ``flush`` already published the resume source, and a
        second publish from the source would only add a stale manifest
        to the shared root. Idempotent like :meth:`detach`."""
        try:
            header, _ = self._call(
                "detach",
                {
                    "tenant": tenant_id,
                    "checkpoint": bool(checkpoint),
                    "timeout": self._effective_timeout(timeout_s),
                },
                timeout_s=timeout_s,
            )
        except ServeError as e:
            if isinstance(e, WireError) or e.reason != "unknown_tenant":
                raise
            header = {}
        return header.get("checkpoint")

    def adopt_tenant(
        self,
        tenant_id: str,
        exported: Dict[str, Any],
        *,
        restored_seq: int,
        timeout_s: Any = _UNSET,
    ) -> int:
        """Install an exported tenant state after an ``attach`` on this
        host restored its checkpoint at ``restored_seq``, then replay the
        un-durable tail of the replay buffer (everything above the
        restored watermark) in order. Batches at or below the watermark
        came back through the checkpoint; the server dedups any overlap.
        Returns the number of batches replayed. Raises a structured
        ``checkpoint_behind`` error when the restored watermark is BELOW
        the exported durable one: entries the old host acked durable were
        already pruned from the replay buffer, so a restore that does not
        carry them (a non-shared checkpoint root, a lost directory) can
        only produce silently wrong results — refuse instead."""
        exported_durable = int(exported["durable_seq"])
        if restored_seq < exported_durable:
            raise ServeError(
                "checkpoint_behind",
                f"tenant {tenant_id!r}: restored checkpoint watermark "
                f"{restored_seq} < acked durable watermark "
                f"{exported_durable}; batches in between exist in neither "
                "the checkpoint nor the replay buffer (are the hosts "
                "sharing one checkpoint root?).",
            )
        with self._lock:
            attached = self._tenants.get(tenant_id)
        # the router attaches on this host BEFORE adopting, so the codec
        # that attach negotiated carries into the replayed submits
        state = _ClientTenant(0, attached.codec if attached else "raw")
        state.next_seq = int(exported["next_seq"])
        state.durable_seq = max(exported_durable, restored_seq)
        state.replay = deque(
            (int(seq), tuple(args))
            for seq, args in exported["replay"]
            if int(seq) > state.durable_seq
        )
        with self._lock:
            self._tenants[tenant_id] = state
        with state.lock:
            replayed = self._send_replay_entries(
                tenant_id, state, timeout_s
            )
        if replayed and _obs._enabled:
            _obs.counter(
                "serve.router.replays", float(replayed), tenant=tenant_id
            )
        return replayed

    def adopt_attached(self, tenant_id: str, last_seq: int) -> None:
        """Install client-side wire state for a tenant that is ALREADY
        attached server-side (ISSUE 20: a recovered router re-adopting a
        live tenant — ``attach`` would raise ``duplicate_tenant``, and a
        detach/re-attach round-trip would discard queued batches). Seeds
        the seq cursor from the host's reported ``last_seq`` so the next
        submit continues the exactly-once stream; the codec stays "raw"
        (frames are self-describing — a codec is a per-attach bandwidth
        negotiation, not a correctness requirement). The replay buffer
        starts empty: everything at or below ``last_seq`` is applied on
        the host, and nothing above it was ever submitted through this
        client. Idempotent; refuses to clobber live local state."""
        with self._lock:
            if tenant_id not in self._tenants:
                self._tenants[tenant_id] = _ClientTenant(int(last_seq))

"""``torcheval_tpu.serve``: a fault-contained multi-tenant eval service.

The library's serving front end (ISSUE 8, ROADMAP item 3): one persistent
:class:`EvalDaemon` owns the device mesh and serves many concurrent eval
streams (*tenants*), each backed by a
:class:`~torcheval_tpu.metrics.MetricCollection` —

* **async ingestion** over bounded per-tenant queues with admission
  control and explicit backpressure (:class:`AdmissionError` /
  :class:`BackpressureError`: reject-with-reason, never unbounded growth);
* **batch coalescing** — tenants with identical batch signatures share
  ONE compiled window-step program (the deferred window programs key on
  canonical positional member keys, never tenant names), with a
  control-first fallback lane so coalescing never delays a result;
* **fault containment** — a poisoned batch or a raising compute
  quarantines exactly that tenant (:class:`TenantQuarantinedError`, the
  cause attached) while every other tenant proceeds; an idle tenant's
  watchdog deadline evicts it through an atomic ``resilience.save``
  checkpoint (:class:`TenantEvictedError` carries the path) and a
  re-``attach`` resumes bit-identically;
* **per-tenant observability** — ingest/shed/quarantine/eviction
  counters, queue-depth histograms and per-tenant spans in the standard
  obs registry and Chrome trace, plus ``EvalDaemon.health()`` (local) /
  ``health(sync=True)`` (all ranks, one collective round).

See docs/robustness.md ("Serving") for the tenant lifecycle and the
failure-semantics table, and ``bench.py``'s ``config7_serve_tenants_*``
rows for the multi-tenant throughput contract.
"""

from torcheval_tpu.serve.daemon import EvalDaemon
from torcheval_tpu.serve.errors import (
    AdmissionError,
    BackpressureError,
    ServeError,
    TenantError,
    TenantEvictedError,
    TenantQuarantinedError,
)
from torcheval_tpu.serve.tenant import TenantHandle, TenantStatus

__all__ = [
    "AdmissionError",
    "BackpressureError",
    "EvalDaemon",
    "ServeError",
    "TenantError",
    "TenantEvictedError",
    "TenantHandle",
    "TenantQuarantinedError",
    "TenantStatus",
]

"""``torcheval_tpu.serve``: a fault-contained multi-tenant eval service.

The library's serving front end (ISSUE 8, ROADMAP item 3): one persistent
:class:`EvalDaemon` owns the device mesh and serves many concurrent eval
streams (*tenants*), each backed by a
:class:`~torcheval_tpu.metrics.MetricCollection` —

* **async ingestion** over bounded per-tenant queues with admission
  control and explicit backpressure (:class:`AdmissionError` /
  :class:`BackpressureError`: reject-with-reason, never unbounded growth);
* **batch coalescing** — tenants with identical batch signatures share
  ONE compiled window-step program (the deferred window programs key on
  canonical positional member keys, never tenant names), with a
  control-first fallback lane so coalescing never delays a result;
* **fault containment** — a poisoned batch or a raising compute
  quarantines exactly that tenant (:class:`TenantQuarantinedError`, the
  cause attached) while every other tenant proceeds; an idle tenant's
  watchdog deadline evicts it through an atomic ``resilience.save``
  checkpoint (:class:`TenantEvictedError` carries the path) and a
  re-``attach`` resumes bit-identically;
* **per-tenant observability** — ingest/shed/quarantine/eviction
  counters, queue-depth histograms and per-tenant spans in the standard
  obs registry and Chrome trace, plus ``EvalDaemon.health()`` (local) /
  ``health(sync=True)`` (all ranks, one collective round).

Since ISSUE 11 ingest is a zero-copy, overlapped pipeline
(``ingest.py``): frame payloads land in a pooled, size-classed host
staging buffer and decode as zero-copy views; each serving pass moves a
whole coalesced signature group to the device in ONE transfer (identical
broadcast batches transfer once); and eval windows double-buffer —
window N+1 fills and transfers while window N's donated step executes.
The client side coalesces too: ``EvalClient(submit_buffer=K)`` ships K
booked batches per ``submit_many`` frame through a scatter-gather packer.
See docs/performance.md ("Ingest pipeline") for the stage diagram and
the buffer aliasing/recycling contract.

Since ISSUE 10 the service also crosses machines — a stdlib-only network
layer on top of the same daemon:

* **wire** (``wire.py``) — length-prefixed JSON + npz framing, an
  :class:`EvalServer` TCP front end per daemon, structured errors
  crossing with their ``retryable`` classification intact;
* **client** (``client.py``) — :class:`EvalClient` with per-request
  deadlines, exponential backoff + jitter, a per-host circuit breaker,
  bounded in-flight, and idempotent submits (per-tenant monotonic
  sequence numbers + a bounded replay buffer: at-least-once on the wire,
  exactly-once into the metric state);
* **router** (``router.py``) — :class:`EvalRouter` places tenants across
  hosts (rendezvous hashing), health-probes them, and on host failure or
  explicit ``drain`` migrates tenants by restoring their shared-root
  checkpoints on a survivor and replaying the un-durable tail.

Since ISSUE 16 the wire also *streams telemetry*: an ``obs_push`` frame
kind carries O(changed) registry deltas + timeline events + each
daemon's structured ``load_report`` on a per-subscription timer
(``EvalClient.subscribe_obs`` — degrading to ``health()`` polling
against old peers), and the router folds the streams into
``EvalRouter.fleet_status()`` / ``fleet_chrome_trace()`` with staleness
marking. See docs/observability.md ("Fleet telemetry").

Since ISSUE 19 the fleet is *elastic*: placement weights the rendezvous
draw by each host's folded load report (stale/draining hosts are
ineligible for new tenants), a background rebalancer migrates tenants
off hot hosts live with hysteresis (dwell time, improvement threshold,
bounded moves per pass), a hot tenant's stream can be *split* across
hosts as replica tenants (per-replica exactly-once; ``compute()`` merges
bit-identically), and ``EvalRouter.add_host`` / ``remove_host`` plus a
pluggable :class:`ScalingPolicy` (:class:`HeadroomScalingPolicy`) scale
the fleet at runtime. See docs/robustness.md ("Elastic fleet").

See docs/robustness.md ("Serving", "Cluster") for the tenant lifecycle,
the failure-semantics table and the migration contract, and ``bench.py``'s
``config7_serve_tenants_*`` / ``config8_cluster_*`` rows for the
throughput contracts.
"""

from torcheval_tpu.serve.client import EvalClient, ObsSubscription, metric_spec
from torcheval_tpu.serve.daemon import EvalDaemon
from torcheval_tpu.serve.errors import (
    AdmissionError,
    BackpressureError,
    ServeError,
    TenantError,
    TenantEvictedError,
    TenantQuarantinedError,
    WireError,
)
from torcheval_tpu.serve.router import (
    EvalRouter,
    HeadroomScalingPolicy,
    ScalingPolicy,
)
from torcheval_tpu.serve.tenant import TenantHandle, TenantStatus
from torcheval_tpu.serve.wire import EvalServer

__all__ = [
    "AdmissionError",
    "BackpressureError",
    "EvalClient",
    "EvalDaemon",
    "EvalRouter",
    "EvalServer",
    "HeadroomScalingPolicy",
    "ObsSubscription",
    "ScalingPolicy",
    "ServeError",
    "TenantError",
    "TenantEvictedError",
    "TenantHandle",
    "TenantQuarantinedError",
    "TenantStatus",
    "WireError",
    "metric_spec",
]
